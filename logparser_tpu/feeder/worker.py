"""Feeder worker loop: shard payloads -> framed, device-ready batches.

Each worker owns a deterministic subset of the shard plan (shard i goes
to worker ``i % N``) and pushes :class:`EncodedBatch` items into its own
BOUNDED queue — a full queue blocks the worker, which is the whole
backpressure story (the device consumer's drain rate caps host read
rate; nothing buffers unboundedly).

Framing is exactly ``TpuBatchParser.parse_blob``'s: the same
:func:`logparser_tpu.native.encode_blob` packs each batch's line bytes
into the padded ``[B, L]`` uint8 buffer (trailing-newline empty segment
dropped, one trailing ``\\r`` per line stripped), so feeder output is
byte-identical to single-process ``parse_blob`` over the same corpus.
The module is jax-free and picklable — it runs inside ``spawn``ed
worker processes that must never acquire the device.
"""
from __future__ import annotations

import time
import traceback
from dataclasses import dataclass, field
from queue import Full
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .shards import Shard, _Source, read_shard_payload

# Queue message kinds (worker -> consumer).
MSG_BATCH = "batch"
MSG_SHARD_DONE = "shard_done"
MSG_DONE = "done"
MSG_ERROR = "error"


@dataclass
class EncodedBatch:
    """One framed batch: the raw line bytes (kept for lazy oracle rescue
    and byte-parity checks) plus the device-ready encoded buffers.

    ``TpuBatchParser.parse_encoded`` / ``parse_batch_stream`` adopt this
    directly — the consumer process never re-scans the payload."""

    shard: int                  # global shard index
    index: int                  # batch index within the shard
    payload: bytes              # the batch's raw line bytes (with '\n's)
    buf: np.ndarray             # [B, L] uint8 (unpadded batch dim)
    lengths: np.ndarray         # [B] int32
    overflow: List[int] = field(default_factory=list)
    n_lines: int = 0
    read_s: float = 0.0         # this batch's share of the shard read
    encode_s: float = 0.0       # framing wall time (worker-side)

    @property
    def source_bytes(self) -> int:
        return len(self.payload)

    @property
    def order_key(self) -> Tuple[int, int]:
        return (self.shard, self.index)


def split_batches(payload: bytes, batch_lines: int) -> List[Tuple[int, int]]:
    """Line-aligned (start, end) byte ranges of successive
    ``batch_lines``-line groups of ``payload`` (last group takes the
    remainder; a trailing newline ends the last line, it never starts an
    empty one — encode_blob's framing)."""
    if not payload:
        return []
    arr = np.frombuffer(payload, dtype=np.uint8)
    nl = np.flatnonzero(arr == 0x0A)
    # Line starts: 0 plus every newline+1 that still begins a line.
    starts = np.concatenate(([0], nl + 1))
    if payload.endswith(b"\n"):
        starts = starts[:-1]
    n = len(starts)
    out: List[Tuple[int, int]] = []
    for b0 in range(0, n, max(1, batch_lines)):
        b1 = b0 + max(1, batch_lines)
        end = int(starts[b1]) if b1 < n else len(payload)
        out.append((int(starts[b0]), end))
    return out


def run_worker(
    worker_id: int,
    sources: Sequence[_Source],
    shards: Sequence[Shard],
    out_q,
    batch_lines: int,
    line_len: int,
    stop_event,
    delay_s: float = 0.0,
) -> None:
    """Read + frame this worker's shards, in shard order, into ``out_q``.

    ``stop_event`` aborts blocked puts so an abandoned pool never leaks
    a worker wedged on a full queue.  ``delay_s`` sleeps after each
    batch — a shaping/test hook (slow-source simulation)."""
    from ..native import encode_blob

    def put(item) -> bool:
        while True:
            if stop_event.is_set():
                return False
            try:
                out_q.put(item, timeout=0.1)
                return True
            except Full:  # same class for both queue flavors
                continue

    try:
        for shard in shards:
            t_shard = time.perf_counter()
            t0 = time.perf_counter()
            payload = read_shard_payload(sources[shard.source], shard)
            read_s = time.perf_counter() - t0
            ranges = split_batches(payload, batch_lines)
            shard_lines = 0
            for bi, (p0, p1) in enumerate(ranges):
                chunk = payload[p0:p1]
                t0 = time.perf_counter()
                buf, lengths, overflow = encode_blob(chunk, line_len=line_len)
                encode_s = time.perf_counter() - t0
                n = int(buf.shape[0]) if len(chunk) else 0
                shard_lines += n
                eb = EncodedBatch(
                    shard=shard.index,
                    index=bi,
                    payload=chunk,
                    buf=buf,
                    lengths=lengths,
                    overflow=list(overflow),
                    n_lines=n,
                    read_s=read_s / max(1, len(ranges)),
                    encode_s=encode_s,
                )
                if not put((MSG_BATCH, eb)):
                    return
                if delay_s:
                    time.sleep(delay_s)
            if not put((
                MSG_SHARD_DONE,
                shard.index,
                time.perf_counter() - t_shard,
                shard_lines,
                len(payload),
            )):
                return
        put((MSG_DONE, worker_id))
    except Exception:  # noqa: BLE001 — relay to the consumer, never die silent
        try:
            put((MSG_ERROR, worker_id, traceback.format_exc()))
        except Exception:  # noqa: BLE001 — queue already torn down
            pass


# Threads-mode producers can update the shared queue-depth gauge on every
# put (the consumer only sees depth at get time); process-mode workers
# live in another registry, so the parent samples qsize() instead.
def make_instrumented_queue(q, depth_cb: Optional[Callable[[], None]]):
    if depth_cb is None:
        return q

    class _Wrapped:
        def put(self, item, timeout=None):
            q.put(item, timeout=timeout)
            depth_cb()

        def get(self, timeout=None):
            return q.get(timeout=timeout)

        def qsize(self) -> int:
            return q.qsize()

    return _Wrapped()
