"""Feeder worker loop: shard payloads -> framed, device-ready batches.

Each worker owns a deterministic subset of the shard plan (shard i goes
to worker ``i % N``) and pushes batch messages into its own queue.
Backpressure is transport-specific but always producer-blocking:

- **ring** transport (the default for process pools): the batch body is
  framed directly into a shared-memory slot and only a tiny
  :class:`~logparser_tpu.feeder.ring.SlotFrame` descriptor crosses the
  queue — an exhausted free-slot queue blocks the worker until the
  consumer releases a slot;
- **pickle** transport (escape hatch / fallback): the whole
  :class:`EncodedBatch` is pickled through a BOUNDED queue — a full
  queue blocks the worker.

Either way the device consumer's drain rate caps host read rate;
nothing buffers unboundedly and nothing is ever dropped.

Framing is exactly ``TpuBatchParser.parse_blob``'s: the same
:func:`logparser_tpu.native.encode_blob` packs each batch's line bytes
into the padded ``[B, L]`` uint8 buffer (trailing-newline empty segment
dropped, one trailing ``\\r`` per line stripped), so feeder output is
byte-identical to single-process ``parse_blob`` over the same corpus —
on BOTH transports (the parity suite pins it).  The module is jax-free
and picklable — it runs inside ``spawn``ed worker processes that must
never acquire the device.
"""
from __future__ import annotations

import logging
import time
import traceback
from dataclasses import dataclass, field
from queue import Full
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .shards import Shard, _Source, read_shard_payload


def note_teardown_error(logger: logging.Logger, site: str,
                        exc: BaseException) -> None:
    """Teardown/cleanup failures must not be silent: count them
    (``feeder_teardown_errors_total{site=...}``) and warn once per
    distinct message — a leak-shaped failure (unlinkable arena, wedged
    queue) repeated across pools is exactly the drip a long-lived host
    needs to see.  Shared by pool.py and ring.py; in a WORKER process
    the counter lands in the child's registry (invisible to the
    consumer) but the warning still reaches its stderr."""
    from ..observability import log_warning_once, metrics

    metrics().increment(
        "feeder_teardown_errors_total", labels={"site": site}
    )
    log_warning_once(
        logger,
        f"feeder teardown: {site} failed "
        f"({type(exc).__name__}: {exc})",
    )

# Queue message kinds (worker -> consumer).
MSG_BATCH = "batch"          # pickled EncodedBatch body
MSG_SLOT = "slot"            # ring SlotFrame descriptor (body in shm)
MSG_SHARD_DONE = "shard_done"
MSG_DONE = "done"
MSG_ERROR = "error"


@dataclass
class EncodedBatch:
    """One framed batch: the raw line bytes (kept for lazy oracle rescue
    and byte-parity checks) plus the device-ready encoded buffers.

    ``TpuBatchParser.parse_encoded`` / ``parse_batch_stream`` adopt this
    directly — the consumer process never re-scans the payload.  The
    ring transport's :class:`~logparser_tpu.feeder.ring.RingBatch`
    subclass backs the same fields with shared-memory slot views."""

    shard: int                  # global shard index
    index: int                  # batch index within the shard
    payload: bytes              # the batch's raw line bytes (with '\n's)
    buf: np.ndarray             # [B, L] uint8 (unpadded batch dim)
    lengths: np.ndarray         # [B] int32
    overflow: List[int] = field(default_factory=list)
    n_lines: int = 0
    read_s: float = 0.0         # this batch's share of the shard read
    encode_s: float = 0.0       # framing wall time (worker-side)
    slot_wait_s: float = 0.0    # ring backpressure wait (0 for pickle)

    @property
    def source_bytes(self) -> int:
        return len(self.payload)

    @property
    def order_key(self) -> Tuple[int, int]:
        return (self.shard, self.index)

    def release(self) -> None:
        """Slot-lease hook: a plain (owned) batch holds no lease."""

    def detach(self) -> "EncodedBatch":
        """Owned-copy hook: a plain batch already owns its arrays."""
        return self


def split_batches(payload: bytes, batch_lines: int) -> List[Tuple[int, int]]:
    """Line-aligned (start, end) byte ranges of successive
    ``batch_lines``-line groups of ``payload`` (last group takes the
    remainder; a trailing newline ends the last line, it never starts an
    empty one — encode_blob's framing)."""
    if not payload:
        return []
    arr = np.frombuffer(payload, dtype=np.uint8)
    nl = np.flatnonzero(arr == 0x0A)
    # Line starts: 0 plus every newline+1 that still begins a line.
    starts = np.concatenate(([0], nl + 1))
    if payload.endswith(b"\n"):
        starts = starts[:-1]
    n = len(starts)
    out: List[Tuple[int, int]] = []
    for b0 in range(0, n, max(1, batch_lines)):
        b1 = b0 + max(1, batch_lines)
        end = int(starts[b1]) if b1 < n else len(payload)
        out.append((int(starts[b0]), end))
    return out


def run_worker(
    worker_id: int,
    sources: Sequence[_Source],
    shards: Sequence[Shard],
    out_q,
    batch_lines: int,
    line_len: int,
    stop_event,
    delay_s: float = 0.0,
    ring=None,
    puts=None,
    watch_parent: bool = False,
    resume: Optional[Dict[int, int]] = None,
    chaos=None,
) -> None:
    """Read + frame this worker's shards, in shard order, into ``out_q``.

    ``stop_event`` aborts blocked puts AND blocked slot acquires so an
    abandoned pool never leaks a worker wedged on a full queue or an
    exhausted ring.  ``delay_s`` sleeps after each batch — a
    shaping/test hook (slow-source simulation).  ``ring`` selects the
    shared-memory transport: a :class:`~logparser_tpu.feeder.ring.
    RingSpec` (process workers attach by name) or a ready
    ``SlotWriter`` (thread workers share the pool's mapping).  ``puts``
    is an optional shared put-counter (``multiprocessing.Value``) the
    parent reads to keep the ``feeder_queue_depth`` gauge live for
    process workers (a child process cannot touch the parent's metrics
    registry).  ``watch_parent`` arms the orphan watch — process
    workers only: there ``mp.parent_process()`` IS the consumer, while
    a thread worker's is whatever spawned the consumer, and that dying
    says nothing about the consumer's health.

    ``resume`` maps global shard index -> number of leading batches to
    SKIP — how a respawned worker replays a partially-delivered shard
    from the last delivered batch boundary (``split_batches`` is
    deterministic over (payload, batch_lines), so the replayed suffix
    is byte-identical to what the dead incarnation would have sent).
    Batch indices keep their original values.  ``chaos`` is an optional
    :class:`~logparser_tpu.tools.chaos.ChaosSpec` arming the
    fault-injection hooks (parsed by the pool — env vars do not reach
    forkserver children reliably)."""
    from ..native import encode_blob

    hard_exit: Tuple = ()
    if chaos is not None:
        from ..tools.chaos import WorkerChaos, _ChaosHardExit

        hard_exit = (_ChaosHardExit,)
        chaos = WorkerChaos(chaos, worker_id, is_process=watch_parent)

    writer = None
    if ring is not None:
        from .ring import SlotWriter

        writer = ring if isinstance(ring, SlotWriter) else SlotWriter(ring)

    stop = _StopWatch(stop_event, watch_parent=watch_parent)

    def put(item) -> bool:
        if chaos is not None:
            chaos.before_put()
        while True:
            if stop.is_set():
                return False
            try:
                out_q.put(item, timeout=0.1)
                if puts is not None:
                    with puts.get_lock():
                        puts.value += 1
                return True
            except Full:  # same class for both queue flavors
                continue

    def emit_batch(shard, bi, chunk, read_share) -> bool:
        """Frame + ship one batch over the active transport.  Returns
        False when the stop event cut a blocked wait short."""
        if writer is not None:
            from .ring import SlotOverflow

            got = writer.acquire(stop)
            if got is None:
                return False
            slot, wait_s = got
            t0 = time.perf_counter()
            try:
                if chaos is not None and chaos.force_overflow():
                    raise SlotOverflow("chaos: forced slot overflow")
                n, L, overflow = writer.frame(chunk, line_len, slot)
            except SlotOverflow:
                # This one batch outgrew the slot (pathological line
                # bucket): give the slot back and ship it pickled.
                writer.putback(slot)
            else:
                from .ring import SlotFrame

                desc = SlotFrame(
                    shard=shard.index, index=bi, slot=slot,
                    n_lines=n if len(chunk) else 0, line_len=L,
                    payload_len=len(chunk), overflow=overflow,
                    read_s=read_share,
                    encode_s=time.perf_counter() - t0,
                    slot_wait_s=wait_s,
                    generation=writer.next_generation(slot),
                )
                if chaos is not None:
                    chaos.corrupt(desc)
                if not put((MSG_SLOT, desc)):
                    writer.putback(slot)
                    return False
                writer.note_sent(slot)
                return True
        else:
            wait_s = 0.0
        t0 = time.perf_counter()
        buf, lengths, overflow = encode_blob(chunk, line_len=line_len)
        encode_s = time.perf_counter() - t0
        n = int(buf.shape[0]) if len(chunk) else 0
        eb = EncodedBatch(
            shard=shard.index,
            index=bi,
            payload=chunk,
            buf=buf,
            lengths=lengths,
            overflow=list(overflow),
            n_lines=n,
            read_s=read_share,
            encode_s=encode_s,
            slot_wait_s=wait_s,
        )
        return put((MSG_BATCH, eb))

    try:
        for shard in shards:
            skip = resume.get(shard.index, 0) if resume else 0
            if chaos is not None:
                chaos.on_shard_start(shard.index)
            t_shard = time.perf_counter()
            t0 = time.perf_counter()
            payload = read_shard_payload(sources[shard.source], shard)
            read_s = time.perf_counter() - t0
            ranges = split_batches(payload, batch_lines)
            shard_lines = 0
            read_share = read_s / max(1, len(ranges))
            for bi, (p0, p1) in enumerate(ranges):
                if bi < skip:
                    continue  # replay: already delivered by a previous life
                if chaos is not None:
                    chaos.before_batch()
                chunk = payload[p0:p1]
                if not emit_batch(shard, bi, chunk, read_share):
                    return
                if chaos is not None:
                    chaos.after_emit()
                shard_lines += _count_lines(chunk)
                if delay_s:
                    time.sleep(delay_s)
            if chaos is not None and chaos.drop_done(shard.index):
                return  # injected protocol stall: vanish without DONE
            if not put((
                MSG_SHARD_DONE,
                shard.index,
                time.perf_counter() - t_shard,
                shard_lines,
                len(payload),
            )):
                return
        put((MSG_DONE, worker_id))
    except hard_exit:
        return  # injected hard crash (thread flavor): no relay, no DONE
    except Exception:  # noqa: BLE001 — relay to the consumer, never die silent
        try:
            put((MSG_ERROR, worker_id, traceback.format_exc()))
        except Exception:  # noqa: BLE001 — queue already torn down
            pass
    finally:
        if writer is not None:
            writer.close()


class _StopWatch:
    """``stop_event`` plus orphan detection: a worker whose logical
    parent (the pool's consumer process) died without close() — SIGKILL,
    test-harness timeout — must exit on its own.  Wedged orphans would
    otherwise spin on their put/acquire loops forever, holding the
    resource-tracker pipe open (so crashed-consumer arenas never get
    unlinked) and any inherited stdout/stderr pipes (so a harness
    waiting on the consumer's output hangs).  The parent sentinel is
    polled at most once per second.  Armed ONLY for process workers
    (``watch_parent=True``): for them ``mp.parent_process()`` is the
    consumer itself; a thread worker runs INSIDE the consumer, whose
    own parent dying is not the consumer dying."""

    __slots__ = ("_event", "_parent", "_next_check")

    def __init__(self, stop_event, watch_parent: bool = False):
        self._event = stop_event
        self._parent = None
        if watch_parent:
            try:
                import multiprocessing as mp

                self._parent = mp.parent_process()
            except Exception:  # noqa: BLE001 — detection is best-effort
                pass
        self._next_check = 0.0

    def is_set(self) -> bool:
        if self._event.is_set():
            return True
        if self._parent is not None:
            now = time.monotonic()
            if now >= self._next_check:
                self._next_check = now + 1.0
                if not self._parent.is_alive():
                    return True
        return False


def _count_lines(chunk: bytes) -> int:
    """encode_blob's line count without framing: a trailing newline
    ends the last line, it never starts a new one.  THE home of that
    counting rule — shard_done accounting here and ``_BlobLines``'s
    bytes branch (tpu/batch.py) both call it; keep any framing-rule
    change in one place."""
    if not chunk:
        return 0
    n = chunk.count(b"\n")
    return n if chunk.endswith(b"\n") else n + 1


# Threads-mode producers can update the shared queue-depth gauge on every
# put (the consumer only sees depth at get time); process-mode workers
# live in another registry, so the parent tracks depth with a shared
# put-counter (the ``puts`` arg of run_worker) minus its own get count.
def make_instrumented_queue(q, depth_cb: Optional[Callable[[], None]]):
    if depth_cb is None:
        return q

    class _Wrapped:
        def put(self, item, timeout=None):
            q.put(item, timeout=timeout)
            depth_cb()

        def get(self, timeout=None):
            return q.get(timeout=timeout)

        def qsize(self) -> int:
            return q.qsize()

    return _Wrapped()
