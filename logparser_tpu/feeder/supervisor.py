"""Supervision policy for the feeder fabric: the decision state machine.

PR-3/PR-5 built an ingest fabric that was fail-stop: one crashed worker
raised :class:`~logparser_tpu.feeder.pool.FeederError` and aborted the
whole run, one wedged shard had no route around it, and a ring fault
meant silent corruption or a dead pipeline.  This module is the brain of
the recovery layer — a PURE state machine (no processes, no queues, no
sleeps) that :class:`~logparser_tpu.feeder.pool.FeederPool` consults on
every fault and whose :class:`Decision` the pool then executes:

- a crashed / errored / deadline-stalled worker is **respawned** with a
  bounded per-rung restart budget and exponential backoff; the pool
  replays the in-flight shard from the last fully-DELIVERED batch
  boundary (framing is deterministic, so recovered output is
  byte-identical to an undisturbed run);
- a shard that kills its workers ``poison_threshold`` times (default 2)
  is **quarantined**: the pool re-frames it in-process over the host
  (numpy) framer path instead of feeding it to yet another doomed
  worker — the run completes, the event is counted
  (``feeder_shards_quarantined_total``), and only a shard that cannot
  even be READ in-process aborts the run;
- repeated transport faults walk the worker down the **demotion
  ladder** — ``ring -> pickle -> inline`` for process pools,
  ``ring -> inline`` for thread pools (``demote_transport``, the
  degradation counterpart of ``resolve_transport``): ring descriptor /
  generation faults demote off the ring after ``ring_fault_threshold``,
  a slot-overflow storm after ``overflow_demotion_threshold``, and a
  worker that exhausts its restart budget carries its next incarnation
  one rung down (``feeder_transport_demotions_total``).  ``inline``
  means a THREAD in the consumer process — the rung below forking;
- a worker that still dies at the bottom of the ladder quarantines
  every shard it dies on — progress stays monotonic, the run always
  terminates.

The pool's one-producer/one-consumer queue discipline is what makes all
of this safe: respawns always get a FRESH queue (and a fresh ring), so
a replayed shard can never interleave with stale in-flight messages.
Everything here is jax-free; tests drive the machine directly
(``tests/test_faults.py``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


class WorkerFault(RuntimeError):
    """One observed worker failure.  ``kind``:

    - ``"died"``: the producer vanished without reporting (SIGKILL,
      os._exit, a thread that returned mid-shard);
    - ``"error"``: the worker relayed MSG_ERROR (carries the traceback);
    - ``"stalled"``: the consumer waited past the worker deadline on an
      alive but silent producer;
    - ``"protocol"``: the worker broke the message protocol (wrong
      shard, DONE before its shards completed).
    """

    def __init__(self, kind: str, worker: int, detail: str = ""):
        super().__init__(
            f"feeder worker {worker} fault ({kind})"
            + (f":\n{detail}" if detail else "")
        )
        self.kind = kind
        self.worker = worker
        self.detail = detail


@dataclass
class SupervisorPolicy:
    """Tunables of the recovery layer (docs/FEEDER.md "Failure model").

    Defaults favor fast tests and fast production recovery: the backoff
    exists to stop a crash-looping worker from burning a core, not to
    ride out multi-second outages — quarantine/demotion handle those.
    """

    #: Restart budget PER WORKER PER LADDER RUNG; exceeding it demotes
    #: the worker's transport one rung (fresh budget at the new rung).
    max_restarts: int = 3
    #: A shard whose worker dies this many times is quarantined.
    poison_threshold: int = 2
    #: Exponential backoff before respawn k: base * 2**(k-1), capped.
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    #: Ring descriptor/generation faults per worker before it is demoted
    #: off the ring (each fault is already recovered per batch by the
    #: in-process re-frame; the threshold stops the drip).
    ring_fault_threshold: int = 2
    #: Slot-overflow pickle fallbacks per worker before the ring is
    #: clearly mis-sized for this corpus and the worker leaves it.
    overflow_demotion_threshold: int = 16
    #: Consumer wait on an ALIVE but silent producer before it is
    #: declared stalled and respawned.  None disables (default): with a
    #: slow consumer holding slot leases, a stalled-looking worker may
    #: just be backpressured — enable it when the consumer is known to
    #: release promptly (the chaos/bench harnesses do).
    worker_deadline_s: Optional[float] = None


@dataclass
class Decision:
    """What the pool should do about one fault."""

    action: str                      # "respawn" | "quarantine"
    transport: str                   # transport of the (re)spawned worker
    backoff_s: float = 0.0
    demoted_from: Optional[str] = None


def demote_transport(current: str, mode: str) -> Optional[str]:
    """The next rung DOWN from ``current`` for a pool in ``mode``
    (the degradation counterpart of ``resolve_transport``): process
    pools walk ring -> pickle -> inline (a consumer-side thread),
    thread pools ring -> inline; None below the bottom."""
    if mode == "process":
        return {"ring": "pickle", "pickle": "inline"}.get(current)
    return {"ring": "inline"}.get(current)


class FeederSupervisor:
    """Per-pool fault bookkeeping + the decision rules above.  The pool
    owns exactly one; every method is consumer-thread-only (no locks)."""

    def __init__(self, policy: SupervisorPolicy, workers: int, mode: str,
                 transport: str):
        self.policy = policy
        self.mode = mode
        self.transport_of: List[str] = [transport] * workers
        self._rung_restarts = [0] * workers
        #: Respawns EXECUTED (pool-incremented alongside
        #: feeder_worker_restarts_total, so stats() and /metrics agree);
        #: a fault whose worker owed nothing decides but never respawns.
        self.total_restarts = 0
        self.shard_kills: Dict[int, int] = {}
        self.ring_faults = [0] * workers
        self.overflow_fallbacks = [0] * workers
        self.quarantined: List[int] = []
        self.demotions: List[Tuple[int, str, str]] = []
        self.recovery_s = 0.0  # pool-accounted: backoff + respawn wall

    # -- worker death / error / stall -----------------------------------

    def on_worker_fault(self, worker: int, shard_index: int) -> Decision:
        """One dead/errored/stalled worker while shard ``shard_index``
        was draining.  Order of precedence: exhausted restart budget
        demotes (or, at the bottom rung, quarantines), then the shard's
        kill count may quarantine, else respawn with backoff."""
        kills = self.shard_kills[shard_index] = (
            self.shard_kills.get(shard_index, 0) + 1
        )
        self._rung_restarts[worker] += 1
        transport = self.transport_of[worker]
        demoted_from: Optional[str] = None
        if self._rung_restarts[worker] > self.policy.max_restarts:
            nxt = demote_transport(transport, self.mode)
            if nxt is None:
                # Bottom of the ladder and still dying: route around the
                # data instead of the worker.
                return self._record(
                    Decision("quarantine", transport),
                    worker=worker, shard=shard_index)
            demoted_from, transport = transport, nxt
            self._note_demotion(worker, nxt)
        if kills >= self.policy.poison_threshold:
            return self._record(
                Decision("quarantine", transport,
                         demoted_from=demoted_from),
                worker=worker, shard=shard_index)
        backoff = min(
            self.policy.backoff_max_s,
            self.policy.backoff_base_s
            * (2 ** (self._rung_restarts[worker] - 1)),
        )
        return self._record(
            Decision("respawn", transport, backoff, demoted_from),
            worker=worker, shard=shard_index)

    # -- ring-lane faults ------------------------------------------------

    def on_ring_fault(self, worker: int) -> Optional[Decision]:
        """One descriptor/generation fault (already recovered per batch
        by the pool's in-process re-frame).  Returns a demotion Decision
        once the per-worker threshold trips, else None (keep going)."""
        self.ring_faults[worker] += 1
        if (self.transport_of[worker] == "ring"
                and self.ring_faults[worker]
                >= self.policy.ring_fault_threshold):
            return self._demote_decision(worker)
        return None

    def on_overflow_fallback(self, worker: int) -> Optional[Decision]:
        """One slot-overflow pickle fallback (benign per batch); a storm
        of them means the ring is mis-sized — demote at the threshold."""
        self.overflow_fallbacks[worker] += 1
        if (self.transport_of[worker] == "ring"
                and self.overflow_fallbacks[worker]
                == self.policy.overflow_demotion_threshold):
            return self._demote_decision(worker)
        return None

    def _demote_decision(self, worker: int) -> Decision:
        current = self.transport_of[worker]
        nxt = demote_transport(current, self.mode) or "inline"
        self._note_demotion(worker, nxt)
        return self._record(
            Decision("respawn", nxt, demoted_from=current), worker=worker)

    @staticmethod
    def _record(decision: Decision, **fields: object) -> Decision:
        """Every supervisory decision is a flight-recorder event: the
        recovery itself is silent by design (byte-identical output), so
        the ring is the only per-incident record that survives a later
        crash (docs/OBSERVABILITY.md "Flight recorder")."""
        from ..tracing import flight_event

        flight_event("feeder_decision", action=decision.action,
                     transport=decision.transport,
                     demoted_from=decision.demoted_from, **fields)
        return decision

    def _note_demotion(self, worker: int, new_transport: str) -> None:
        self.demotions.append(
            (worker, self.transport_of[worker], new_transport)
        )
        self.transport_of[worker] = new_transport
        self._rung_restarts[worker] = 0  # fresh budget at the new rung

    # -- reporting -------------------------------------------------------

    def summary(self) -> Dict[str, object]:
        return {
            "worker_restarts": self.total_restarts,
            "shards_quarantined": len(self.quarantined),
            "quarantined_shards": list(self.quarantined),
            "transport_demotions": len(self.demotions),
            "ring_faults": int(sum(self.ring_faults)),
            "recovery_s": round(self.recovery_s, 4),
        }
