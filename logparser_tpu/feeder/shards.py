"""Shard planner: byte-range shards with newline-boundary healing.

The multichip projection in BASELINE.md needs ~83 GB/s of input feed —
far beyond one reader thread — so the corpus must be split into
independent byte ranges that many workers can frame in parallel.  The
split semantics mirror the reference's Hadoop InputFormat
(ApacheHttpdLogfileInputFormat + LineRecordReader): raw shards tile the
byte space blindly, and healing assigns every LINE to exactly one shard:

    a shard [start, end) owns every line whose FIRST byte lies in
    [start, end).

A reader therefore skips forward from ``start`` to the first line start
(unless ``start`` is 0 or the previous byte is a newline), and reads
PAST ``end`` to finish the last line it owns — so a line spanning a
shard boundary belongs to the shard where it began, and a line longer
than a whole shard leaves the middle shards empty.  Healed payloads of
consecutive shards concatenate back to the original byte stream exactly
(the byte-parity contract tests/test_feeder.py pins).

Everything here is jax-free (workers must import it without touching
the device runtime).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import IO, List, Optional, Sequence, Tuple, Union

SourceT = Union[str, bytes, bytearray, memoryview, "os.PathLike[str]"]

#: Default raw shard size: large enough that healing and per-shard setup
#: are noise, small enough that a handful of shards spread over few
#: workers (the reference's FileInputFormat defaults to the HDFS block).
DEFAULT_SHARD_BYTES = 8 << 20


@dataclass(frozen=True)
class Shard:
    """One raw byte range of one source.  ``index`` is the global shard
    order (across all sources) — delivery order and worker assignment
    both derive from it."""

    index: int          # global shard index (delivery order)
    source: int         # index into the pool's source list
    start: int          # raw range start (byte offset, pre-healing)
    end: int            # raw range end (exclusive, pre-healing)

    @property
    def raw_bytes(self) -> int:
        return self.end - self.start


class _Source:
    """Normalized input source: an in-memory blob or a file path."""

    __slots__ = ("kind", "blob", "path", "size")

    def __init__(self, src: SourceT):
        self.blob: bytes = b""
        self.path: Optional[str] = None
        if isinstance(src, (bytes, bytearray, memoryview)):
            self.kind = "blob"
            self.blob = bytes(src)
            self.size = len(self.blob)
        else:
            self.kind = "file"
            self.path = os.fspath(src)
            self.size = os.path.getsize(self.path)

    def describe(self) -> str:
        return self.path if self.kind == "file" else f"<blob {self.size}B>"


def normalize_sources(sources: Sequence[SourceT]) -> List[_Source]:
    return [_Source(s) for s in sources]


def plan_shards(
    sources: Sequence[_Source], shard_bytes: int = DEFAULT_SHARD_BYTES
) -> List[Shard]:
    """Tile every source into raw ``shard_bytes`` ranges (the last shard
    of a source takes the remainder).  Healing happens at read time —
    the plan itself never opens a file (the reference computes splits
    from file LENGTHS only, FileInputFormat.getSplits)."""
    if shard_bytes <= 0:
        raise ValueError(f"shard_bytes must be positive, got {shard_bytes}")
    shards: List[Shard] = []
    for si, src in enumerate(sources):
        start = 0
        while start < src.size:
            end = min(start + shard_bytes, src.size)
            shards.append(Shard(len(shards), si, start, end))
            start = end
    return shards


# ---------------------------------------------------------------------------
# pod-scale plan subsetting: disjoint per-host shard ranges
# ---------------------------------------------------------------------------


def host_shard_range(n_shards: int, n_hosts: int,
                     host_index: int) -> Tuple[int, int]:
    """The contiguous ``[start, end)`` slice of global shard indices that
    host ``host_index`` of an ``n_hosts`` pod owns — balanced (sizes
    differ by at most one), disjoint, and tiling ``range(n_shards)``
    exactly.  Contiguous ranges (not strided) keep each host's reads
    sequential within a source file and make a dead host's unfinished
    work one run of consecutive uncommitted shards (docs/JOBS.md "Pod
    jobs")."""
    if n_hosts <= 0:
        raise ValueError(f"n_hosts must be positive, got {n_hosts}")
    if not 0 <= host_index < n_hosts:
        raise ValueError(
            f"host_index {host_index} outside [0, {n_hosts})"
        )
    base, rem = divmod(n_shards, n_hosts)
    start = host_index * base + min(host_index, rem)
    end = start + base + (1 if host_index < rem else 0)
    return start, end


def shards_for_host(plan: Sequence[Shard], n_hosts: int,
                    host_index: int) -> List[Shard]:
    """The subset of a global shard plan one pod host owns (see
    :func:`host_shard_range`).  Shards keep their GLOBAL indices — the
    job runner renumbers for the feeder pool and maps back at commit
    time, so every host's manifest speaks the same global shard
    vocabulary and the manifests merge without translation."""
    start, end = host_shard_range(len(plan), n_hosts, host_index)
    return [s for s in plan if start <= s.index < end]


# ---------------------------------------------------------------------------
# healing: raw range -> owned line range
# ---------------------------------------------------------------------------


def line_start_at_or_after(blob: bytes, pos: int) -> int:
    """Offset of the first line START at or after ``pos`` (len(blob)
    when none): 0 stays 0, a position just after a newline is already a
    line start, anything else skips to just past the next newline."""
    if pos <= 0:
        return 0
    if pos >= len(blob):
        return len(blob)
    if blob[pos - 1 : pos] == b"\n":
        return pos
    j = blob.find(b"\n", pos)
    return len(blob) if j < 0 else j + 1


def healed_range(blob: bytes, start: int, end: int) -> Tuple[int, int]:
    """The line-owned byte range of raw shard [start, end): every line
    starting inside the raw range, whole.  Consecutive shards' healed
    ranges tile the blob exactly."""
    return (
        line_start_at_or_after(blob, start),
        line_start_at_or_after(blob, end),
    )


def healed_payload(blob: bytes, start: int, end: int) -> bytes:
    p0, p1 = healed_range(blob, start, end)
    return blob[p0:p1] if p1 > p0 else b""


def _file_line_start_at_or_after(
    f: IO[bytes], pos: int, size: int, chunk: int = 1 << 16
) -> int:
    """:func:`line_start_at_or_after` over an open binary file."""
    if pos <= 0:
        return 0
    if pos >= size:
        return size
    f.seek(pos - 1)
    if f.read(1) == b"\n":
        return pos
    off = pos
    while off < size:
        data = f.read(chunk)
        if not data:
            return size
        j = data.find(b"\n")
        if j >= 0:
            return off + j + 1
        off += len(data)
    return size


def read_shard_payload(src: _Source, shard: Shard) -> bytes:
    """The healed payload bytes of one shard (whole lines only; empty
    when the raw range contains no line start)."""
    if src.kind == "blob":
        return healed_payload(src.blob, shard.start, shard.end)
    with open(src.path, "rb") as f:  # type: ignore[arg-type]
        p0 = _file_line_start_at_or_after(f, shard.start, src.size)
        p1 = _file_line_start_at_or_after(f, shard.end, src.size)
        if p1 <= p0:
            return b""
        f.seek(p0)
        return f.read(p1 - p0)
