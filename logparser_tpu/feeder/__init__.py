"""Sharded feeder subsystem: the multi-process ingest fabric.

Turns raw log sources into a steady, ordered stream of framed,
device-ready batches so the device tier is never input-starved
(docs/FEEDER.md; BASELINE.md's 83 GB/s feed question).  Three layers:

- :mod:`~logparser_tpu.feeder.shards` — byte-range shard planning with
  newline-boundary healing (the reference InputFormat's split
  semantics: a line belongs to the shard where it starts);
- :mod:`~logparser_tpu.feeder.worker` — the jax-free worker loop that
  reads + frames shards with the ``parse_blob`` framing;
- :mod:`~logparser_tpu.feeder.ring` — the zero-copy shared-memory slot
  transport (per-worker arenas, descriptor queues, slot-exhaustion
  backpressure);
- :mod:`~logparser_tpu.feeder.pool` — :class:`FeederPool`, the consumer
  API: ``batches()`` (ordered EncodedBatch stream with backpressure)
  and ``feed(parser)`` (BatchResults via ``parse_batch_stream``);
- :mod:`~logparser_tpu.feeder.supervisor` — the fault-recovery policy:
  bounded worker respawn with shard replay, poison-shard quarantine,
  and the ring -> pickle -> inline transport demotion ladder (armed by
  default; exercised on purpose by ``tools/chaos.py``).
"""
from .pool import (  # noqa: F401
    CHAOS_ENV,
    DEFAULT_BATCH_LINES,
    PICKLE_ENV,
    FeederError,
    FeederPool,
    default_feeder_workers,
    deregister_backpressure_source,
    queue_backpressure,
    register_backpressure_source,
    resolve_transport,
)
from .ring import (  # noqa: F401
    RING_NAME_PREFIX,
    RingBatch,
    RingFault,
    SlotFrame,
    SlotOverflow,
    SlotRing,
    SlotWriter,
    ring_available,
    slot_layout,
)
from .supervisor import (  # noqa: F401
    Decision,
    FeederSupervisor,
    SupervisorPolicy,
    WorkerFault,
    demote_transport,
)
from .shards import (  # noqa: F401
    DEFAULT_SHARD_BYTES,
    Shard,
    healed_payload,
    healed_range,
    host_shard_range,
    line_start_at_or_after,
    normalize_sources,
    plan_shards,
    shards_for_host,
)
from .worker import EncodedBatch, split_batches  # noqa: F401
