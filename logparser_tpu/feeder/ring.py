"""Shared-memory ring transport: the zero-copy worker->consumer lane.

The pickled transport (PR 3) serializes every ``EncodedBatch`` crossing
a worker->consumer queue: the frame arrays and the retained rescue
payload are pickled, copied through the pipe in 64 KiB chunks, and
unpickled again — two-plus full memcpy passes (one of them with the
consumer's GIL held) per batch before the device sees a byte.  This
module replaces the payload lane with a per-worker
``multiprocessing.shared_memory`` arena carved into fixed slots:

- the worker ACQUIRES a free slot id from its ``free_q`` (an empty free
  queue blocks the worker — slot exhaustion IS the backpressure signal
  the bounded queues provide in pickle mode);
- it frames the batch DIRECTLY into slot-backed numpy views (the exact
  ``parse_blob`` framing via :func:`logparser_tpu.native.encode_blob`'s
  ``alloc`` hook) and memcpys the raw payload bytes beside it (kept for
  lazy oracle rescue, same contract as the pickled transport);
- the descriptor queue carries only a tiny :class:`SlotFrame` (slot id,
  shapes, sequence, timings) — the multi-MB batch body never touches a
  pipe;
- the consumer MAPS the slot zero-copy (``np.frombuffer`` views over
  the arena) into a :class:`RingBatch` and RELEASES the slot id back to
  ``free_q`` once the batch is done with it (after device upload and
  rescue-payload use — ``parse_batch_stream`` releases post-
  materialization; ``FeederPool.batches()`` detaches by default).

Slot layout (``slot_bytes``-aligned offsets, 8-byte slot alignment so
the int32 lengths view is aligned)::

    [0 .. 4*B)                lengths  int32[B]
    [align8(4*B) .. +B*L)     buf      uint8[B, L]
    [.. +payload_len)         payload  raw line bytes (with '\\n's)

A batch whose framed size exceeds ``slot_bytes`` (a pathological line
bucket) falls back to the pickled lane for that one batch — the ring
degrades per batch, never wholesale.

Cleanup: the consumer process CREATES the arenas and the resource
tracker holds their registrations, so a crashed consumer still gets
its segments unlinked.  Workers only attach — pre-3.13 that registers
with the tracker too, but forkserver/spawn children SHARE the parent's
tracker process, so the attach-side registration dedupes into the one
the consumer already holds (no premature unlink, no double-unregister;
see ``SlotWriter.__init__``) and the single unlink on pool close — or
on consumer crash, via the tracker — clears it.  Orphaned workers
(consumer SIGKILLed) self-terminate via the parent-death watch in
``run_worker``, so nothing pins the tracker pipe open.  The module is
jax-free and import-cheap (worker processes load it).
"""
from __future__ import annotations

import secrets
import time
from dataclasses import dataclass, field
from queue import Empty
from typing import Any, List, Optional, Tuple

import numpy as np

import logging

from .worker import EncodedBatch, note_teardown_error

LOG = logging.getLogger(__name__)

#: Slot alignment: keeps every slot's int32 lengths view 4-byte aligned
#: (and leaves room for wider frame dtypes later).
SLOT_ALIGN = 8

#: /dev/shm segment name prefix — the leak checks in feeder_smoke and
#: tests key on it.
RING_NAME_PREFIX = "lpring"


def ring_available() -> bool:
    """Can this platform back a shared-memory ring at all?"""
    try:
        from multiprocessing import shared_memory  # noqa: F401

        return True
    except ImportError:
        return False


def _shared_memory_cls():
    """SharedMemory with a close() tolerant of live exported views.

    The stream's tail batches materialize AFTER the pool (and its
    arenas) close — their payload views legitimately outlive close(),
    which makes ``mmap.close()`` raise BufferError both at close time
    and again from ``SharedMemory.__del__`` at GC.  The segment is
    still unlinked either way (names never leak); the mapping itself
    dies with the last view, so swallowing the BufferError is correct,
    not a leak."""
    from multiprocessing import shared_memory

    class _QuietSharedMemory(shared_memory.SharedMemory):
        def close(self) -> None:
            try:
                super().close()
            except BufferError:
                pass

    return _QuietSharedMemory


class SlotOverflow(Exception):
    """The framed batch does not fit one slot (fall back to pickle)."""


class RingFault(RuntimeError):
    """A descriptor failed map-time validation.  ``reason``:

    - ``"generation"``: the descriptor's slot-use generation does not
      match the consumer's ledger — a slot-reuse race or a stale/
      duplicated descriptor; the slot's contents cannot be trusted;
    - ``"descriptor"``: structurally invalid fields (slot id out of
      range, negative shapes, a layout that exceeds the slot).

    The supervised pool recovers per batch (in-process re-frame of the
    expected batch — delivery order makes it unambiguous) and demotes
    the worker off the ring past ``ring_fault_threshold`` faults; an
    unsupervised pool surfaces the fault as a FeederError instead of
    handing corrupt bytes downstream.

    ``stale`` marks the generation sub-case where the descriptor's
    generation is BEHIND the ledger: a replay of a send already mapped
    and delivered.  Re-framing that batch would duplicate it in the
    stream and releasing its slot would double-free a lease someone
    else may hold — the pool DROPS a stale descriptor instead."""

    def __init__(self, reason: str, detail: str = "", stale: bool = False):
        super().__init__(f"ring fault ({reason}): {detail}")
        self.reason = reason
        self.stale = stale


def slot_layout(n: int, line_len: int, payload_len: int) -> Tuple[int, int, int]:
    """(buf_offset, payload_offset, total_bytes) of one framed batch
    inside its slot — the single layout definition writer and reader
    share."""
    lengths_bytes = 4 * max(n, 1)
    buf_off = -(-lengths_bytes // SLOT_ALIGN) * SLOT_ALIGN
    payload_off = buf_off + max(n, 1) * line_len
    return buf_off, payload_off, payload_off + payload_len


@dataclass
class SlotFrame:
    """The descriptor that crosses the queue instead of the batch body.
    Everything here is a handful of ints/floats — pickling it is noise."""

    shard: int                  # global shard index
    index: int                  # batch index within the shard
    slot: int                   # slot id inside the worker's arena
    n_lines: int
    line_len: int               # framed L (buf is [n_lines, line_len])
    payload_len: int
    overflow: List[int] = field(default_factory=list)
    read_s: float = 0.0
    encode_s: float = 0.0
    slot_wait_s: float = 0.0    # time the worker blocked acquiring the slot
    #: Slot-use generation: how many descriptors have been SENT for this
    #: slot before this one.  The consumer keeps its own per-slot ledger
    #: of mapped descriptors; since every sent descriptor is mapped
    #: exactly once and in order, the two agree unless a reuse race, a
    #: duplicate, or corruption intervened — verified in SlotRing.map,
    #: counted as feeder_ring_generation_mismatch_total by the pool.
    generation: int = 0


@dataclass
class RingSpec:
    """Picklable handle a worker needs to attach one arena: segment
    name, geometry, and the free-slot queue (ForkingPickler ships
    mp.Queue through Process args)."""

    name: str
    slot_bytes: int
    n_slots: int
    free_q: Any


@dataclass
class RingBatch(EncodedBatch):
    """An EncodedBatch whose payload/buf/lengths are zero-copy views
    into a ring slot.  The slot stays leased to this batch until
    :meth:`release` — ``parse_batch_stream`` releases after the batch's
    materialization (device upload done, rescue payload consumed);
    :meth:`detach` converts to an owned plain batch and releases
    immediately (the ``FeederPool.batches()`` default)."""

    ring: Any = None            # consumer-side SlotRing
    slot: int = -1
    released: bool = False

    def release(self) -> None:
        if self.ring is not None and not self.released:
            self.released = True
            self.ring.release(self.slot)

    def detach(self) -> EncodedBatch:
        eb = EncodedBatch(
            shard=self.shard,
            index=self.index,
            payload=bytes(self.payload),
            buf=np.array(self.buf, copy=True),
            lengths=np.array(self.lengths, copy=True),
            overflow=list(self.overflow),
            n_lines=self.n_lines,
            read_s=self.read_s,
            encode_s=self.encode_s,
        )
        eb.slot_wait_s = self.slot_wait_s
        self.release()
        return eb


class SlotWriter:
    """Worker-side arena access: acquire a slot, frame into it.

    In process mode the worker attaches by name from a :class:`RingSpec`
    (and drops its attach-side resource_tracker registration, see module
    docstring); in thread-ring mode the pool passes its own ``shm`` so
    all threads share one mapping."""

    def __init__(self, spec: RingSpec, shm: Any = None):
        self.spec = spec
        # Per-slot count of descriptors SENT (not merely acquired:
        # overflow/stop putbacks send nothing and must not advance the
        # generation the consumer's ledger expects).
        self._sent = [0] * spec.n_slots
        self._owns_attach = shm is None
        if shm is None:
            # Attaching registers with the resource tracker too (pre-3.13
            # has no track=False) — harmless here: forkserver/spawn
            # children share the PARENT's tracker process, so the
            # registration dedupes into the one the creating consumer
            # already holds, and the single unlink on pool close (or on
            # consumer crash, via the tracker) clears it.
            shm = _shared_memory_cls()(name=spec.name)
        self.shm = shm

    def acquire(self, stop_event) -> Optional[Tuple[int, float]]:
        """Next free slot id, blocking until one is released (the
        backpressure wait) — ``(slot, waited_seconds)``, or None when
        ``stop_event`` fired first."""
        t0 = time.perf_counter()
        while True:
            if stop_event.is_set():
                return None
            try:
                slot = self.spec.free_q.get(timeout=0.1)
                return int(slot), time.perf_counter() - t0
            except Empty:
                continue

    def putback(self, slot: int) -> None:
        """Return an acquired-but-unused slot (overflow/stop paths)."""
        self.spec.free_q.put(slot)

    def next_generation(self, slot: int) -> int:
        """The generation a descriptor for ``slot`` must carry NOW
        (descriptors sent so far); advance with :meth:`note_sent` only
        after the descriptor actually crossed the queue."""
        return self._sent[slot]

    def note_sent(self, slot: int) -> None:
        self._sent[slot] += 1

    def frame(self, chunk, line_len: int, slot: int):
        """Frame ``chunk`` (one batch's raw line bytes) directly into
        ``slot``: encode_blob packs the [B, L] buffer and lengths into
        slot-backed views, the payload is memcpy'd beside them.  Returns
        ``(n_lines, L, overflow)``; raises :class:`SlotOverflow` when
        the framed batch cannot fit the slot."""
        from ..native import encode_blob

        base = slot * self.spec.slot_bytes
        mv = self.shm.buf
        carved: List[int] = []

        def alloc(n: int, L: int):
            buf_off, payload_off, total = slot_layout(n, L, len(chunk))
            if total > self.spec.slot_bytes:
                raise SlotOverflow(
                    f"batch needs {total}B > slot_bytes={self.spec.slot_bytes}"
                )
            carved[:] = [payload_off]
            lengths = np.frombuffer(mv, dtype=np.int32, count=n, offset=base)
            buf = np.frombuffer(
                mv, dtype=np.uint8, count=n * L, offset=base + buf_off
            ).reshape(n, L)
            return buf, lengths

        buf, lengths, overflow = encode_blob(
            chunk, line_len=line_len, alloc=alloc
        )
        (payload_off,) = carved
        if len(chunk):
            dst = np.frombuffer(
                mv, dtype=np.uint8, count=len(chunk), offset=base + payload_off
            )
            dst[:] = np.frombuffer(chunk, dtype=np.uint8)
        return int(buf.shape[0]), int(buf.shape[1]), list(overflow)

    def close(self) -> None:
        if self._owns_attach:
            try:
                self.shm.close()
            except Exception as e:  # noqa: BLE001 — teardown is best-effort
                note_teardown_error(LOG, "SlotWriter.close", e)


class SlotRing:
    """Consumer-side owner of one worker's arena: creates the segment,
    seeds the free queue, maps descriptors into :class:`RingBatch`
    views, recycles released slots, and unlinks on close."""

    def __init__(self, slot_bytes: int, n_slots: int, free_q: Any,
                 name_hint: str = "", prefault: bool = True):
        shm_cls = _shared_memory_cls()
        if slot_bytes % SLOT_ALIGN:
            slot_bytes += SLOT_ALIGN - slot_bytes % SLOT_ALIGN
        self.slot_bytes = int(slot_bytes)
        self.n_slots = int(n_slots)
        self.free_q = free_q
        shm = None
        for _ in range(8):
            name = (f"{RING_NAME_PREFIX}_{name_hint}_"
                    f"{secrets.token_hex(4)}").strip("_")
            try:
                shm = shm_cls(
                    name=name, create=True, size=self.slot_bytes * self.n_slots
                )
                break
            except FileExistsError:  # pragma: no cover — 32-bit token race
                continue
        if shm is None:  # pragma: no cover
            raise RuntimeError("could not allocate a uniquely-named arena")
        self.shm = shm
        # Pre-fault the whole arena once at create time: tmpfs pages are
        # allocated HERE (startup, outside any measured steady window)
        # instead of as major faults inside the workers' first framing
        # passes — the difference between a warm ring and one that pays
        # page-allocation latency for its first n_slots batches.
        # ``prefault=False`` skips it (supervised respawns: the rebuild
        # happens MID-RUN with the consumer waiting, so lazy faults —
        # overlapped with worker framing — beat a serial multi-MB zero
        # pass).
        if prefault:
            np.frombuffer(shm.buf, dtype=np.uint8)[:] = 0
        self._closed = False
        # Consumer-side generation ledger: descriptors MAPPED per slot
        # (the counterpart of SlotWriter._sent — see SlotFrame.generation).
        self._gen = [0] * self.n_slots
        for slot in range(self.n_slots):
            free_q.put(slot)

    def spec(self) -> RingSpec:
        return RingSpec(self.shm.name, self.slot_bytes, self.n_slots,
                        self.free_q)

    def map(self, f: SlotFrame) -> RingBatch:
        """One descriptor -> zero-copy RingBatch over the slot's views.

        Validates the descriptor FIRST (:class:`RingFault`): a corrupt
        slot id or layout would otherwise read out of the arena, and a
        stale generation would silently deliver a recycled slot's bytes
        as this batch's."""
        if not (0 <= f.slot < self.n_slots):
            raise RingFault(
                "descriptor", f"slot {f.slot} outside [0, {self.n_slots})"
            )
        # A descriptor carrying generation >= the ledger is a SEND not
        # yet consumed — it advances the ledger whether it maps or
        # faults below, so a faulted slot's next legitimate descriptor
        # still maps cleanly once the pool releases the slot back.  One
        # carrying generation < the ledger is a replay of a send already
        # consumed (stale duplicate): its generation was counted when
        # the original mapped, so the ledger must NOT move again.
        expected = self._gen[f.slot]
        if f.generation >= expected:
            self._gen[f.slot] += 1
        if f.n_lines < 0 or f.line_len < 0 or f.payload_len < 0:
            raise RingFault(
                "descriptor",
                f"negative shape (n={f.n_lines}, L={f.line_len}, "
                f"payload={f.payload_len})",
            )
        base = f.slot * self.slot_bytes
        n = max(f.n_lines, 1)
        buf_off, payload_off, total = slot_layout(
            n, f.line_len, f.payload_len
        )
        if total > self.slot_bytes:
            raise RingFault(
                "descriptor",
                f"layout needs {total}B > slot_bytes={self.slot_bytes}",
            )
        if f.generation != expected:
            raise RingFault(
                "generation",
                f"slot {f.slot} descriptor generation {f.generation} != "
                f"expected {expected} (slot-reuse race or stale "
                "descriptor)",
                stale=f.generation < expected,
            )
        mv = self.shm.buf
        lengths = np.frombuffer(
            mv, dtype=np.int32, count=n, offset=base
        )[: f.n_lines]
        buf = np.frombuffer(
            mv, dtype=np.uint8, count=n * f.line_len, offset=base + buf_off
        ).reshape(n, f.line_len)[: f.n_lines]
        payload = np.frombuffer(
            mv, dtype=np.uint8, count=f.payload_len, offset=base + payload_off
        )
        return RingBatch(
            shard=f.shard,
            index=f.index,
            payload=payload,
            buf=buf,
            lengths=lengths,
            overflow=list(f.overflow),
            n_lines=f.n_lines,
            read_s=f.read_s,
            encode_s=f.encode_s,
            slot_wait_s=f.slot_wait_s,
            ring=self,
            slot=f.slot,
        )

    def release(self, slot: int) -> None:
        if not self._closed:
            try:
                self.free_q.put(slot)
            except Exception as e:  # noqa: BLE001 — queue torn down mid-release
                note_teardown_error(LOG, "SlotRing.release", e)

    def inplace_bytes(self, f: SlotFrame) -> int:
        """Bytes this descriptor delivered through the arena instead of
        the pipe (the feeder_ring_bytes_inplace_total increment)."""
        return 4 * f.n_lines + f.n_lines * f.line_len + f.payload_len

    def close(self) -> None:
        """Unmap and unlink the segment.  Idempotent; outstanding
        RingBatch views die with the mapping — callers must detach
        batches that outlive the pool."""
        if self._closed:
            return
        self._closed = True
        # mp.Queue's feeder thread would otherwise keep the process
        # alive waiting to flush released slot ids nobody will read.
        if hasattr(self.free_q, "cancel_join_thread"):
            self.free_q.cancel_join_thread()
        try:
            self.shm.close()
        except BufferError:
            # Live RingBatch views pin the mapping: the segment still
            # gets unlinked below (names never leak); the mapping itself
            # goes when the last view does.  Expected, not counted.
            pass
        except Exception as e:  # noqa: BLE001
            note_teardown_error(LOG, "SlotRing.close", e)
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass  # already unlinked (resource tracker beat us to it)
        except Exception as e:  # noqa: BLE001
            note_teardown_error(LOG, "SlotRing.unlink", e)
