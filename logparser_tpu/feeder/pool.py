"""FeederPool: the multi-process ingest fabric behind one iterator.

``FeederPool(sources).batches()`` turns raw log sources (file paths or
in-memory blobs) into a steady, ORDERED stream of framed
:class:`~logparser_tpu.feeder.worker.EncodedBatch` items:

- the shard planner tiles the sources into byte-range shards with
  newline-boundary healing (``feeder/shards.py`` — the reference's
  InputFormat split semantics);
- N workers (processes by default, threads as fallback or on request)
  read + frame their shards with the ``parse_blob`` framing and ship
  them over one of two TRANSPORTS:

  * ``"ring"`` (process default): each worker frames directly into a
    per-worker shared-memory slot arena (``feeder/ring.py``) and the
    queue carries only small slot descriptors — zero-copy bodies, with
    slot exhaustion as the backpressure signal;
  * ``"pickle"`` (escape hatch ``LOGPARSER_TPU_FEEDER_PICKLE=1``, or
    the fallback when shared memory is unavailable): whole batches
    pickle through BOUNDED per-worker queues — a full queue blocks its
    worker.  Thread workers default to the direct in-process hand-off
    (``"inline"``; nothing to serialize), but accept ``transport=
    "ring"`` explicitly (the ring mechanics are address-space agnostic
    — tests exercise wraparound/exhaustion without process spawns);

- the consumer drains shards in global order (shard i lives in worker
  ``i % N``'s queue), so delivery order equals single-process
  ``parse_blob`` order with no reorder buffer and no deadlock: each
  queue has exactly one producer and one consumer.

``batches()`` DETACHES ring batches by default (owned copies, slot
released immediately) so callers may hold arbitrarily many; pass
``detach=False`` to receive zero-copy :class:`~logparser_tpu.feeder.
ring.RingBatch` views and call ``release()`` yourself.  ``feed(parser)``
pipes the zero-copy stream through ``TpuBatchParser.parse_batch_stream``
(which adopts pre-encoded batches without re-framing, stages the next
batch's H2D upload while the current one computes, and releases each
slot after the batch materializes), yielding one BatchResult per batch
in corpus order.

Telemetry (the PR-2 metrics registry, docs/OBSERVABILITY.md):
``feeder_bytes_read_total``, ``feeder_lines_total``,
``feeder_batches_total``, ``feeder_shards_total`` counters; the
``feeder_queue_depth`` gauge (producer-updated in threads mode, shared
put-counters minus consumer gets in process mode — live on every
platform, qsize-less or not); ``feeder_starvation_seconds_total`` (wall
time the consumer spent blocked on an empty queue — the "is the chip
starving" number); ring counters ``feeder_ring_slot_wait_seconds_total``
(worker backpressure wait, shipped in descriptors),
``feeder_ring_bytes_inplace_total`` (bytes that crossed via the arena
instead of a pipe) and ``feeder_ring_pickle_fallback_total`` (slot-
overflow batches); per-shard/per-batch stage timings via
``observe_stage`` (``feeder_read``, ``feeder_encode``,
``feeder_shard``).
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence

from ..observability import log_warning_once, metrics, observe_stage
from .shards import (
    DEFAULT_SHARD_BYTES,
    Shard,
    SourceT,
    normalize_sources,
    plan_shards,
)
from .worker import (
    MSG_BATCH,
    MSG_ERROR,
    MSG_SHARD_DONE,
    MSG_SLOT,
    EncodedBatch,
    make_instrumented_queue,
    run_worker,
)

import logging

LOG = logging.getLogger(__name__)

DEFAULT_BATCH_LINES = 16384

#: Escape hatch: force the pickled transport everywhere (parity suite
#: asserts both transports byte-identical; this is the rollback lever).
PICKLE_ENV = "LOGPARSER_TPU_FEEDER_PICKLE"


class FeederError(RuntimeError):
    """A feeder worker died; carries the worker traceback."""


def default_feeder_workers() -> int:
    """Process-parallel framing saturates around the core count; capped
    like the assembly pool so a big host doesn't fork 64 readers."""
    return max(1, min(8, os.cpu_count() or 1))


def resolve_transport(requested: Optional[str], mode: str) -> str:
    """The transport a (request, worker-mode) pair actually runs:
    ``LOGPARSER_TPU_FEEDER_PICKLE=1`` wins over everything (the
    emergency rollback must not be overridable per call site); explicit
    requests are honored next; process pools default to ``ring``
    (falling back to ``pickle`` when shared memory is unavailable) and
    thread pools to the direct ``inline`` hand-off."""
    from ..observability import _env_truthy
    from .ring import ring_available

    if _env_truthy(PICKLE_ENV):
        return "pickle" if mode == "process" else "inline"
    if requested:
        if requested not in ("ring", "pickle", "inline"):
            raise ValueError(f"unknown feeder transport {requested!r}")
        if requested == "ring" and not ring_available():
            return "pickle" if mode == "process" else "inline"
        return requested
    if mode == "process":
        return "ring" if ring_available() else "pickle"
    return "inline"


class FeederPool:
    """See module docstring.  Parameters:

    - ``sources``: file paths and/or bytes blobs, in corpus order.
    - ``workers``: feeder worker count (default
      :func:`default_feeder_workers`, clamped to the shard count).
    - ``shard_bytes``: raw shard size for the planner.
    - ``batch_lines``: lines per emitted batch (the device batch size).
    - ``line_len``: pin the framed ``L`` (0 = per-batch length bucket,
      exactly ``parse_blob``'s default).
    - ``queue_batches``: the backpressure window, in batches — the
      per-worker queue bound (pickle/inline) and the default ring slot
      count basis (``queue_batches + 2`` slots: the extra two cover the
      batch on device and the one materializing).
    - ``transport``: ``"ring"`` / ``"pickle"`` / ``"inline"`` / None
      (auto — see :func:`resolve_transport`).
    - ``ring_slots`` / ``slot_bytes``: ring geometry overrides (slots
      per worker arena; bytes per slot, default sized for
      ``batch_lines`` lines of generous length).
    - ``use_processes``: True/False forces the worker flavor; None
      prefers processes and falls back to threads when multiprocessing
      is unavailable.  Processes default to the ``forkserver`` context
      (``spawn`` where unavailable): the parent may hold an initialized
      device runtime, which plain ``fork`` would duplicate into
      children that must never touch the chip, and ``spawn`` re-runs
      ``__main__`` (bench/driver scripts would re-import heavily).
    - ``worker_delay_s``: per-batch producer sleep (shaping/test hook).
    """

    def __init__(
        self,
        sources: Sequence[SourceT],
        workers: Optional[int] = None,
        shard_bytes: int = DEFAULT_SHARD_BYTES,
        batch_lines: int = DEFAULT_BATCH_LINES,
        line_len: int = 0,
        queue_batches: int = 4,
        transport: Optional[str] = None,
        ring_slots: Optional[int] = None,
        slot_bytes: Optional[int] = None,
        use_processes: Optional[bool] = None,
        mp_context: Optional[str] = None,
        worker_delay_s: float = 0.0,
    ):
        if not sources:
            raise ValueError("FeederPool needs at least one source")
        self._sources = normalize_sources(sources)
        self.shards: List[Shard] = plan_shards(self._sources, shard_bytes)
        n_workers = workers if workers else default_feeder_workers()
        self.workers = max(1, min(int(n_workers), max(1, len(self.shards))))
        self.batch_lines = int(batch_lines)
        self.line_len = int(line_len)
        self.queue_batches = max(1, int(queue_batches))
        self._requested_transport = transport
        self.ring_slots = (
            max(2, int(ring_slots)) if ring_slots
            else self.queue_batches + 2
        )
        # Default slot: room for batch_lines lines at a generous L plus
        # the raw payload — a batch that still doesn't fit (pathological
        # line bucket) ships pickled, so this is a fast path size, not a
        # correctness bound.
        self.slot_bytes = (
            int(slot_bytes) if slot_bytes
            else max(1 << 20, self.batch_lines * 768)
        )
        self._use_processes = use_processes
        self._mp_context = mp_context
        self._worker_delay_s = float(worker_delay_s)
        self.mode: Optional[str] = None  # "process" | "thread" once started
        self.transport: Optional[str] = None  # resolved at start
        self._queues: List[Any] = []
        self._procs: List[Any] = []
        self._rings: List[Any] = []
        self._puts: List[Any] = []      # shared put-counters (process mode)
        self._gets: List[int] = []      # local get-counters (process mode)
        self._stop: Any = None
        self._started = False
        self._closed = False
        self._stats: Dict[str, Any] = {
            "shards": len(self.shards),
            "workers": self.workers,
            "batches": 0,
            "lines": 0,
            "payload_bytes": 0,
            "read_s": 0.0,
            "encode_s": 0.0,
            "starvation_s": 0.0,
            "startup_s": 0.0,
            "wall_s": 0.0,
            "queue_depth_max": 0,
            "queue_depth_mean": 0.0,
            "slot_wait_s": 0.0,
            "bytes_inplace": 0,
            "pickle_fallback_batches": 0,
        }
        self._depth_samples = 0
        self._depth_sum = 0
        self._primed = False  # first item delivered (pipeline filled)

    # -- lifecycle -------------------------------------------------------

    def _start(self) -> None:
        if self._started:
            raise RuntimeError("FeederPool.batches() can only run once")
        self._started = True
        shards_of = [self._worker_plan(self.shards[w :: self.workers])
                     for w in range(self.workers)]
        if self._use_processes is not False:
            try:
                self._start_processes(shards_of)
                return
            except Exception as e:  # noqa: BLE001 — environment-dependent
                if self._use_processes:
                    raise
                self._abort_process_start()
                log_warning_once(
                    LOG,
                    "feeder: multiprocessing unavailable "
                    f"({type(e).__name__}); falling back to threads",
                )
        self._start_threads(shards_of)

    def _abort_process_start(self) -> None:
        """Roll back a half-built process start before the thread
        fallback: unlink any arenas already created (they would
        otherwise sit in /dev/shm until interpreter exit) and clear the
        process-mode depth counters (stale ``_puts`` would make
        ``_queue_depth`` read 0 for the whole thread-mode run)."""
        for r in self._rings:
            r.close()
        self._rings = []
        self._puts = []
        self._gets = []
        self._queues = []
        self._procs = []
        self.transport = None

    def _worker_plan(self, shards: List[Shard]):
        """(sources, shards) restricted to what ONE worker touches: its
        shard subset with source indices remapped into a filtered source
        list — spawned workers must not each receive a pickled copy of
        every in-memory blob in the pool (shard indices stay GLOBAL;
        only source references are localized)."""
        from dataclasses import replace

        used = sorted({s.source for s in shards})
        remap = {g: l for l, g in enumerate(used)}
        return (
            [self._sources[g] for g in used],
            [replace(s, source=remap[s.source]) for s in shards],
        )

    def _build_rings(self, queue_factory) -> List[Any]:
        """One arena per worker, free queues seeded; ``queue_factory``
        makes the free queues (ctx.Queue or queue.Queue)."""
        from .ring import SlotRing

        rings = []
        try:
            for w in range(self.workers):
                rings.append(SlotRing(
                    self.slot_bytes, self.ring_slots, queue_factory(),
                    name_hint=f"{os.getpid()}_{w}",
                ))
        except Exception:
            for r in rings:
                r.close()
            raise
        return rings

    def _start_processes(self, shards_of) -> None:
        import multiprocessing as mp

        method = self._mp_context
        if method is None:
            method = ("forkserver"
                      if "forkserver" in mp.get_all_start_methods()
                      else "spawn")
        ctx = mp.get_context(method)
        self.transport = resolve_transport(self._requested_transport,
                                           "process")
        self._stop = ctx.Event()
        if self.transport == "ring":
            try:
                self._rings = self._build_rings(ctx.Queue)
            except Exception as e:  # noqa: BLE001 — no /dev/shm etc.
                log_warning_once(
                    LOG,
                    "feeder: shared-memory ring unavailable "
                    f"({type(e).__name__}); falling back to pickle",
                )
                self.transport = "pickle"
        # Queue bound by transport: for pickle it IS the backpressure —
        # exactly the documented queue_batches window.  For the ring,
        # slot exhaustion backpressures and the queue only carries small
        # descriptors (at most one per leased slot) plus control
        # messages — sized to never stall a slot-holding worker.
        q_bound = (self.ring_slots + 2 if self.transport == "ring"
                   else self.queue_batches)
        self._queues = [ctx.Queue(maxsize=q_bound)
                        for _ in range(self.workers)]
        self._puts = [ctx.Value("l", 0) for _ in range(self.workers)]
        self._gets = [0] * self.workers
        procs = []
        try:
            for w in range(self.workers):
                w_sources, w_shards = shards_of[w]
                p = ctx.Process(
                    target=run_worker,
                    args=(w, w_sources, w_shards, self._queues[w],
                          self.batch_lines, self.line_len, self._stop,
                          self._worker_delay_s,
                          self._rings[w].spec() if self._rings else None,
                          self._puts[w], True),
                    name=f"logparser-tpu-feeder-{w}",
                    daemon=True,
                )
                p.start()
                procs.append(p)
        except Exception:
            for p in procs:
                p.terminate()
            raise
        self._procs = procs
        self.mode = "process"

    def _start_threads(self, shards_of) -> None:
        self._stop = threading.Event()
        self.transport = resolve_transport(self._requested_transport,
                                           "thread")
        writers: List[Any] = [None] * self.workers
        if self.transport == "ring":
            try:
                self._rings = self._build_rings(_queue.Queue)
                from .ring import SlotWriter

                writers = [SlotWriter(r.spec(), shm=r.shm)
                           for r in self._rings]
            except Exception as e:  # noqa: BLE001
                log_warning_once(
                    LOG,
                    "feeder: shared-memory ring unavailable "
                    f"({type(e).__name__}); falling back to inline",
                )
                self.transport = "inline"
        # Same bound rule as process mode: a thread-ring worker must
        # never stall on the descriptor queue while holding a slot
        # (slot exhaustion is the backpressure there, not the queue).
        q_bound = (self.ring_slots + 2 if self.transport == "ring"
                   else self.queue_batches)
        raw = [_queue.Queue(maxsize=q_bound)
               for _ in range(self.workers)]
        # Producer-side gauge updates: only possible in-process.
        self._queues = raw
        instrumented = [
            make_instrumented_queue(q, self._publish_depth) for q in raw
        ]
        self._procs = []
        for w in range(self.workers):
            w_sources, w_shards = shards_of[w]
            t = threading.Thread(
                target=run_worker,
                args=(w, w_sources, w_shards, instrumented[w],
                      self.batch_lines, self.line_len, self._stop,
                      self._worker_delay_s, writers[w], None),
                name=f"logparser-tpu-feeder-{w}",
                daemon=True,
            )
            t.start()
            self._procs.append(t)
        self.mode = "thread"

    def close(self) -> None:
        """Stop workers, drop queues, unlink ring arenas.  Idempotent;
        also runs on normal exhaustion of :meth:`batches`."""
        if self._closed:
            return
        self._closed = True
        if self._stop is not None:
            self._stop.set()
        # Drain so workers blocked on a full queue observe the stop event
        # promptly instead of at their next 0.1 s put timeout.
        for q in self._queues:
            try:
                while True:
                    q.get_nowait() if hasattr(q, "get_nowait") else q.get(
                        timeout=0
                    )
            except Exception:  # noqa: BLE001 — Empty from either flavor
                pass
        for p in self._procs:
            p.join(timeout=5)
            if hasattr(p, "terminate") and p.is_alive():
                p.terminate()
        for q in self._queues:
            # mp.Queue feeder threads keep the process alive unless
            # cancelled; plain queue.Queue has no such method.
            if hasattr(q, "cancel_join_thread"):
                q.cancel_join_thread()
        for r in self._rings:
            r.close()
        metrics().gauge_set("feeder_queue_depth", 0)

    def __enter__(self) -> "FeederPool":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- metrics helpers -------------------------------------------------

    def _queue_depth(self) -> int:
        if self._puts:
            # Process mode: shared put-counters minus this consumer's get
            # counts — live on every platform (macOS mp queues have no
            # qsize) and unaffected by pipe buffering.
            total = 0
            for w in range(self.workers):
                total += max(0, self._puts[w].value - self._gets[w])
            return total
        total = 0
        for q in self._queues:
            try:
                total += q.qsize()
            except (NotImplementedError, OSError):
                return -1  # platform without qsize (macOS mp queues)
        return total

    def _publish_depth(self) -> None:
        depth = self._queue_depth()
        if depth >= 0:
            metrics().gauge_set("feeder_queue_depth", depth)

    def _sample_depth(self) -> None:
        depth = self._queue_depth()
        if depth < 0:
            return
        metrics().gauge_set("feeder_queue_depth", depth)
        self._depth_samples += 1
        self._depth_sum += depth
        if depth > self._stats["queue_depth_max"]:
            self._stats["queue_depth_max"] = depth

    # -- consumption -----------------------------------------------------

    def _get(self, q, worker: int):
        """Blocking dequeue that accounts starvation and watches THIS
        queue's producer (a crashed worker must surface as FeederError,
        not a hang — even while sibling workers are alive and blocked
        on their own full queues)."""
        t_enter = time.perf_counter()
        blocked = 0.0  # time spent in Empty waits only — a successful
        # get's own duration (pipe read + unpickling of a multi-MB
        # batch in pickle mode) is transfer, not starvation.
        while True:
            t0 = time.perf_counter()
            try:
                # Short poll: blocked time is only observable in whole
                # Empty windows, so the window is the accounting grain.
                msg = q.get(timeout=0.05)
                break
            except _queue.Empty:
                blocked += time.perf_counter() - t0
                if not self._procs[worker].is_alive():
                    # Producer gone with its queue empty: it died before
                    # reporting (e.g. SIGKILL).  One grace re-read in
                    # case its final messages were still in flight.
                    try:
                        msg = q.get(timeout=0.5)
                        break
                    except _queue.Empty:
                        raise FeederError(
                            f"feeder worker {worker} exited without "
                            "completing its shards"
                        ) from None
        if self._gets:
            self._gets[worker] += 1
        if not self._primed:
            # Pipeline fill — worker start, first read/frame AND the
            # first item's queue transfer — is startup latency, not
            # starvation: the chip wasn't waiting on a fabric that had
            # ever been ahead of it.  Post-prime gets only ever count
            # their Empty windows (the transfer itself is throughput).
            self._primed = True
            self._stats["startup_s"] = time.perf_counter() - t_enter
        elif blocked > 0:
            self._stats["starvation_s"] += blocked
            metrics().increment("feeder_starvation_seconds_total", blocked)
        self._sample_depth()
        return msg

    def batches(self, detach: bool = True) -> Iterator[EncodedBatch]:
        """The ordered batch stream (single use).  Yields every framed
        batch of every shard, in global shard order, then joins the
        workers and closes the pool.

        ``detach=True`` (default): ring batches are converted to owned
        copies and their slots released immediately — hold as many as
        you like.  ``detach=False``: ring batches arrive as ZERO-COPY
        slot views; the caller must ``release()`` each one (or the ring
        exhausts and the producers block) and must not touch a batch
        after releasing it.  ``feed()`` uses the zero-copy flavor with
        ``parse_batch_stream`` handling the releases."""
        self._start()
        reg = metrics()
        t_start = time.perf_counter()
        try:
            for shard in self.shards:
                worker = shard.index % self.workers
                q = self._queues[worker]
                while True:
                    msg = self._get(q, worker)
                    kind = msg[0]
                    if kind == MSG_SLOT:
                        desc = msg[1]
                        ring = self._rings[worker]
                        reg.increment("feeder_ring_slot_wait_seconds_total",
                                      desc.slot_wait_s)
                        inplace = ring.inplace_bytes(desc)
                        reg.increment("feeder_ring_bytes_inplace_total",
                                      inplace)
                        self._stats["slot_wait_s"] += desc.slot_wait_s
                        self._stats["bytes_inplace"] += inplace
                        eb: EncodedBatch = ring.map(desc)
                    elif kind == MSG_BATCH:
                        eb = msg[1]
                        if self.transport == "ring":
                            # Slot-overflow fallback batch (counted, not
                            # fatal: the ring degrades per batch).  Its
                            # slot-acquire wait still happened — keep the
                            # backpressure signal honest under overflow.
                            self._stats["pickle_fallback_batches"] += 1
                            reg.increment("feeder_ring_pickle_fallback_total")
                            self._stats["slot_wait_s"] += eb.slot_wait_s
                            reg.increment(
                                "feeder_ring_slot_wait_seconds_total",
                                eb.slot_wait_s,
                            )
                    elif kind == MSG_SHARD_DONE:
                        _, sidx, wall_s, n_lines, _nbytes = msg
                        assert sidx == shard.index
                        reg.increment("feeder_shards_total")
                        observe_stage("feeder_shard", wall_s, items=n_lines)
                        break
                    elif kind == MSG_ERROR:
                        raise FeederError(
                            f"feeder worker {msg[1]} failed:\n{msg[2]}"
                        )
                    else:  # MSG_DONE out of order: worker finished early
                        raise FeederError(
                            f"feeder protocol violation: {kind!r} before "
                            f"shard {shard.index} completed"
                        )
                    assert eb.shard == shard.index, (
                        f"feeder ordering violated: got shard "
                        f"{eb.shard}, expected {shard.index}"
                    )
                    self._stats["batches"] += 1
                    self._stats["lines"] += eb.n_lines
                    self._stats["payload_bytes"] += eb.source_bytes
                    self._stats["read_s"] += eb.read_s
                    self._stats["encode_s"] += eb.encode_s
                    reg.increment("feeder_bytes_read_total",
                                  eb.source_bytes)
                    reg.increment("feeder_lines_total", eb.n_lines)
                    reg.increment("feeder_batches_total")
                    observe_stage("feeder_read", eb.read_s,
                                  items=eb.n_lines)
                    observe_stage("feeder_encode", eb.encode_s,
                                  items=eb.n_lines)
                    yield eb.detach() if detach else eb
        finally:
            self._stats["wall_s"] = time.perf_counter() - t_start
            if self._depth_samples:
                self._stats["queue_depth_mean"] = round(
                    self._depth_sum / self._depth_samples, 3
                )
            self.close()

    def feed(self, parser, emit_views: Optional[bool] = None, depth: int = 1):
        """Drive ``parser`` (a TpuBatchParser) over the batch stream:
        yields one BatchResult per batch, in corpus order, with the
        host-side stages of batch k overlapping the device work of batch
        k+1 (``parse_batch_stream`` semantics).  Ring batches flow
        through ZERO-COPY: the stream stages each batch's H2D upload
        straight from (a bucket-padded adoption of) the slot frame and
        releases the slot once the batch materializes — after device
        upload and rescue-payload use."""
        return parser.parse_batch_stream(
            self.batches(detach=False), depth=depth, emit_views=emit_views
        )

    def stats(self) -> Dict[str, Any]:
        """Post-run (or mid-run) feed accounting.  Rates and the
        starvation fraction are computed over the STEADY window (wall
        minus pipeline-fill startup): the one-time worker start + first
        read/frame latency is reported as ``startup_s`` instead of
        polluting the sustained numbers.  ``slot_wait_fraction`` is the
        ring backpressure share: total worker slot-wait over the steady
        window summed across workers (1.0 = every worker blocked the
        whole time = the consumer is the bottleneck)."""
        out = dict(self._stats)
        out["mode"] = self.mode
        out["transport"] = self.transport
        out["ring_slots"] = self.ring_slots
        steady = out["wall_s"] - out["startup_s"]
        if steady > 0:
            out["bytes_per_sec"] = round(out["payload_bytes"] / steady, 1)
            out["starvation_fraction"] = round(
                out["starvation_s"] / steady, 4
            )
            out["slot_wait_fraction"] = round(
                out["slot_wait_s"] / (steady * max(1, self.workers)), 4
            )
        return out
