"""FeederPool: the multi-process ingest fabric behind one iterator.

``FeederPool(sources).batches()`` turns raw log sources (file paths or
in-memory blobs) into a steady, ORDERED stream of framed
:class:`~logparser_tpu.feeder.worker.EncodedBatch` items:

- the shard planner tiles the sources into byte-range shards with
  newline-boundary healing (``feeder/shards.py`` — the reference's
  InputFormat split semantics);
- N workers (processes by default, threads as fallback or on request)
  read + frame their shards with the ``parse_blob`` framing and ship
  them over one of two TRANSPORTS:

  * ``"ring"`` (process default): each worker frames directly into a
    per-worker shared-memory slot arena (``feeder/ring.py``) and the
    queue carries only small slot descriptors — zero-copy bodies, with
    slot exhaustion as the backpressure signal;
  * ``"pickle"`` (escape hatch ``LOGPARSER_TPU_FEEDER_PICKLE=1``, or
    the fallback when shared memory is unavailable): whole batches
    pickle through BOUNDED per-worker queues — a full queue blocks its
    worker.  Thread workers default to the direct in-process hand-off
    (``"inline"``; nothing to serialize), but accept ``transport=
    "ring"`` explicitly (the ring mechanics are address-space agnostic
    — tests exercise wraparound/exhaustion without process spawns);

- the consumer drains shards in global order (shard i lives in worker
  ``i % N``'s queue), so delivery order equals single-process
  ``parse_blob`` order with no reorder buffer and no deadlock: each
  queue has exactly one producer and one consumer.

SUPERVISION (default ON; ``feeder/supervisor.py`` is the policy brain):
a crashed, errored, or deadline-stalled worker no longer aborts the run.
The pool reaps it, requeues its in-flight shard, and respawns it with
bounded per-rung restarts and exponential backoff — the respawned
incarnation REPLAYS the shard from the last fully-delivered batch
boundary (framing is deterministic, so recovered output is
byte-identical to an undisturbed run; respawns always get a fresh queue
and a fresh ring, so replay can never interleave with stale in-flight
messages).  A shard that kills its workers ``poison_threshold`` times
is QUARANTINED: re-framed in-process over the host (numpy) framer path
(``feeder_shards_quarantined_total``) so a poison shard costs its own
throughput, never the run.  Repeated transport faults walk a worker
down the demotion ladder ring -> pickle -> inline
(``feeder_transport_demotions_total``); ring descriptors are
generation-verified at map time, and a mismatch is recovered per batch
by re-framing the expected batch in-process
(``feeder_ring_generation_mismatch_total``) instead of delivering a
recycled slot's bytes.  ``supervise=False`` restores the fail-stop
PR-3/PR-5 behavior (one fault = FeederError).  The chaos harness
(``tools/chaos.py``, ``LOGPARSER_TPU_CHAOS``) injects these failures on
purpose; ``tests/test_faults.py`` and ``make chaos-smoke`` hold the
recovered output to byte parity.

``batches()`` DETACHES ring batches by default (owned copies, slot
released immediately) so callers may hold arbitrarily many; pass
``detach=False`` to receive zero-copy :class:`~logparser_tpu.feeder.
ring.RingBatch` views and call ``release()`` yourself.  ``feed(parser)``
pipes the zero-copy stream through ``TpuBatchParser.parse_batch_stream``
(which adopts pre-encoded batches without re-framing, stages the next
batch's H2D upload while the current one computes, and releases each
slot after the batch materializes), yielding one BatchResult per batch
in corpus order.

Telemetry (the PR-2 metrics registry, docs/OBSERVABILITY.md):
``feeder_bytes_read_total``, ``feeder_lines_total``,
``feeder_batches_total``, ``feeder_shards_total`` counters; the
``feeder_queue_depth`` gauge (producer-updated in threads mode, shared
put-counters minus consumer gets in process mode — live on every
platform, qsize-less or not); ``feeder_starvation_seconds_total`` (wall
time the consumer spent blocked on an empty queue — the "is the chip
starving" number); ring counters ``feeder_ring_slot_wait_seconds_total``
(worker backpressure wait, shipped in descriptors),
``feeder_ring_bytes_inplace_total`` (bytes that crossed via the arena
instead of a pipe) and ``feeder_ring_pickle_fallback_total`` (slot-
overflow batches); recovery counters ``feeder_worker_restarts_total``,
``feeder_shards_requeued_total``, ``feeder_shards_quarantined_total``,
``feeder_transport_demotions_total{from,to}``,
``feeder_ring_generation_mismatch_total``,
``feeder_ring_descriptor_faults_total``,
``feeder_teardown_errors_total{site}``; per-shard/per-batch stage
timings via ``observe_stage`` (``feeder_read``, ``feeder_encode``,
``feeder_shard``).
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time
import weakref
from typing import Any, Dict, Iterator, List, Optional, Sequence

from ..observability import log_warning_once, metrics, observe_stage
from .ring import RingFault
from .shards import (
    DEFAULT_SHARD_BYTES,
    Shard,
    SourceT,
    normalize_sources,
    plan_shards,
    read_shard_payload,
)
from .supervisor import (
    FeederSupervisor,
    SupervisorPolicy,
    WorkerFault,
)
from .worker import (
    MSG_BATCH,
    MSG_ERROR,
    MSG_SHARD_DONE,
    MSG_SLOT,
    EncodedBatch,
    make_instrumented_queue,
    note_teardown_error,
    run_worker,
    split_batches,
)

import logging

LOG = logging.getLogger(__name__)

DEFAULT_BATCH_LINES = 16384

#: Escape hatch: force the pickled transport everywhere (parity suite
#: asserts both transports byte-identical; this is the rollback lever).
PICKLE_ENV = "LOGPARSER_TPU_FEEDER_PICKLE"

#: Fault-injection env var (tools/chaos.py grammar; single definition —
#: the spec is parsed HERE, in the consumer, and shipped to workers
#: through run_worker args: forkserver children inherit the
#: forkserver's env, not the pool's at spawn time).
from ..tools.chaos import CHAOS_ENV  # noqa: E402


class _QueuePump:
    """Consumer-side drainer for a PROCESS worker's queue.

    ``mp.Queue.get(timeout)`` only bounds the readiness poll(): once any
    bytes are buffered, ``recv_bytes()`` blocks until the whole
    length-prefixed frame arrives.  A worker that hard-dies MID-WRITE
    (os._exit / SIGKILL while its queue feeder thread flushes a
    multi-part pickled batch) leaves a partial frame that poll() calls
    ready but recv never completes — a consumer reading the queue
    directly would hang forever inside the very supervision layer meant
    to recover from that death.  The pump takes that risk instead: a
    daemon thread does the blocking gets and forwards messages — FIFO,
    1-deep buffer, so backpressure stays the mp queue bound plus one —
    onto a plain thread-safe buffer the consumer polls.  If the pump
    wedges on a truncated frame it is simply abandoned with its retired
    queue at reap time; the consumer's poll cadence never depends on
    it.  (Thread workers need no pump: queue.Queue hand-off is atomic.)
    """

    __slots__ = ("_q", "_buf", "_stop", "_thread")

    def __init__(self, q, name: str):
        self._q = q
        self._buf: _queue.Queue = _queue.Queue(maxsize=1)
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name=f"logparser-tpu-pump-{name}",
            daemon=True,
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop:
            try:
                item = self._q.get(timeout=0.1)
            except _queue.Empty:
                continue
            except (EOFError, OSError):
                return  # queue torn down under us (pool close)
            while not self._stop:
                try:
                    self._buf.put(item, timeout=0.1)
                    break
                except _queue.Full:
                    continue

    def get(self, timeout=None):
        return self._buf.get(timeout=timeout)

    def get_nowait(self):
        return self._buf.get_nowait()

    def qsize(self) -> int:
        """Messages pulled off the mp queue but not yet consumed."""
        return self._buf.qsize()

    def retire(self) -> None:
        """Stop forwarding (best effort: a pump wedged in recv_bytes
        stays blocked — daemon, abandoned with its dead queue)."""
        self._stop = True


class FeederError(RuntimeError):
    """The feeder could not complete its corpus: with supervision OFF,
    any worker death; with supervision ON, only a shard that failed even
    the in-process quarantine path (i.e. the data itself is unreadable
    or unframeable in this process)."""


def default_feeder_workers() -> int:
    """Process-parallel framing saturates around the core count; capped
    like the assembly pool so a big host doesn't fork 64 readers."""
    return max(1, min(8, os.cpu_count() or 1))


# Live pools, registered at _start() and dropped at close(): the process-
# wide backpressure signal the serving tier's admission control reads
# (docs/SERVICE.md).  A WeakSet so an abandoned pool (consumer crashed
# between start and close) can never pin itself into the signal.
_LIVE_POOLS: "weakref.WeakSet[FeederPool]" = weakref.WeakSet()


def register_backpressure_source(source: Any) -> None:
    """Register any object exposing ``backpressure() -> float`` (a 0-1
    occupancy fraction) into the process-wide
    :func:`queue_backpressure` aggregate.  FeederPools self-register at
    ``_start``; the serving tier's cross-session batch coalescer
    (:mod:`logparser_tpu.service_batching`) registers here so its
    bounded submission queue feeds the SAME admission signal
    (docs/SERVICE.md "Continuous batching").  The WeakSet means an
    abandoned source can never pin itself into the signal."""
    _LIVE_POOLS.add(source)


def deregister_backpressure_source(source: Any) -> None:
    """Drop a :func:`register_backpressure_source` registration (no-op
    when absent)."""
    _LIVE_POOLS.discard(source)


def queue_backpressure() -> float:
    """Aggregate feeder-queue occupancy across every LIVE pool in this
    process as a 0.0–1.0 fraction (worst pool wins: one saturated ring
    means the fabric is not absorbing new work, however idle the
    others).  0.0 when no pool is running or depth is unknowable.  This
    is the signal ``ParseService`` wires its per-request admission
    control to: framed batches waiting at/above the configured fraction
    of the bounded-queue capacity mean the parser is the bottleneck and
    new requests should shed with a structured BUSY frame instead of
    queueing without bound."""
    worst = 0.0
    for pool in list(_LIVE_POOLS):
        try:
            worst = max(worst, pool.backpressure())
        except Exception:  # noqa: BLE001 — a pool mid-teardown reads as idle
            continue
    return worst


def resolve_transport(requested: Optional[str], mode: str) -> str:
    """The transport a (request, worker-mode) pair actually runs:
    ``LOGPARSER_TPU_FEEDER_PICKLE=1`` wins over everything (the
    emergency rollback must not be overridable per call site); explicit
    requests are honored next; process pools default to ``ring``
    (falling back to ``pickle`` when shared memory is unavailable) and
    thread pools to the direct ``inline`` hand-off.  The degradation
    counterpart — the ladder a SUPERVISED worker walks down after
    repeated faults — is :func:`~logparser_tpu.feeder.supervisor.
    demote_transport`."""
    from ..observability import _env_truthy
    from .ring import ring_available

    if _env_truthy(PICKLE_ENV):
        return "pickle" if mode == "process" else "inline"
    if requested:
        if requested not in ("ring", "pickle", "inline"):
            raise ValueError(f"unknown feeder transport {requested!r}")
        if requested == "ring" and not ring_available():
            return "pickle" if mode == "process" else "inline"
        return requested
    if mode == "process":
        return "ring" if ring_available() else "pickle"
    return "inline"


class FeederPool:
    """See module docstring.  Parameters:

    - ``sources``: file paths and/or bytes blobs, in corpus order.
    - ``workers``: feeder worker count (default
      :func:`default_feeder_workers`, clamped to the shard count).
    - ``shard_bytes``: raw shard size for the planner.
    - ``batch_lines``: lines per emitted batch (the device batch size).
    - ``line_len``: pin the framed ``L`` (0 = per-batch length bucket,
      exactly ``parse_blob``'s default).
    - ``queue_batches``: the backpressure window, in batches — the
      per-worker queue bound (pickle/inline) and the default ring slot
      count basis (``queue_batches + 2`` slots: the extra two cover the
      batch on device and the one materializing).
    - ``transport``: ``"ring"`` / ``"pickle"`` / ``"inline"`` / None
      (auto — see :func:`resolve_transport`).
    - ``ring_slots`` / ``slot_bytes``: ring geometry overrides (slots
      per worker arena; bytes per slot, default sized for
      ``batch_lines`` lines of generous length).
    - ``use_processes``: True/False forces the worker flavor; None
      prefers processes and falls back to threads when multiprocessing
      is unavailable.  Processes default to the ``forkserver`` context
      (``spawn`` where unavailable): the parent may hold an initialized
      device runtime, which plain ``fork`` would duplicate into
      children that must never touch the chip, and ``spawn`` re-runs
      ``__main__`` (bench/driver scripts would re-import heavily).
    - ``worker_delay_s``: per-batch producer sleep (shaping/test hook).
    - ``supervise``: worker supervision (default True — crashes are
      recovered, poison shards quarantined, transports demoted; see
      module docstring).  False restores fail-stop FeederError.
    - ``policy``: a :class:`~logparser_tpu.feeder.supervisor.
      SupervisorPolicy` overriding restart/backoff/quarantine tunables.
    - ``chaos``: a :class:`~logparser_tpu.tools.chaos.ChaosSpec` (or its
      string grammar) arming fault injection; default: parse
      ``LOGPARSER_TPU_CHAOS`` when set.
    - ``shutdown_timeout_s``: per-stage close() wait before escalating
      join -> terminate -> kill on a stuck worker.
    """

    def __init__(
        self,
        sources: Sequence[SourceT],
        workers: Optional[int] = None,
        shard_bytes: int = DEFAULT_SHARD_BYTES,
        batch_lines: int = DEFAULT_BATCH_LINES,
        line_len: int = 0,
        queue_batches: int = 4,
        transport: Optional[str] = None,
        ring_slots: Optional[int] = None,
        slot_bytes: Optional[int] = None,
        use_processes: Optional[bool] = None,
        mp_context: Optional[str] = None,
        worker_delay_s: float = 0.0,
        supervise: bool = True,
        policy: Optional[SupervisorPolicy] = None,
        chaos: Any = None,
        shutdown_timeout_s: float = 5.0,
        backpressure_signal: bool = True,
        shard_plan: Optional[Sequence[Shard]] = None,
    ):
        if not sources:
            raise ValueError("FeederPool needs at least one source")
        self._sources = normalize_sources(sources)
        if shard_plan is not None:
            # Caller-supplied plan (the durable job runner resumes a
            # partially-committed corpus by feeding only the shards it
            # still owes).  Indices must be contiguous from 0: shard
            # ownership (``index % workers``) and the positional worker
            # split (``shards[w::workers]``) both assume index ==
            # position — a caller keeping its own identity for each
            # shard renumbers with dataclasses.replace and maps back by
            # position (logparser_tpu/jobs does exactly this).
            shards = list(shard_plan)
            for i, s in enumerate(shards):
                if s.index != i:
                    raise ValueError(
                        "shard_plan indices must be contiguous from 0 "
                        f"(shard at position {i} carries index {s.index})"
                    )
                if not 0 <= s.source < len(self._sources):
                    raise ValueError(
                        f"shard_plan references source {s.source} of "
                        f"{len(self._sources)}"
                    )
            self.shards: List[Shard] = shards
        else:
            self.shards = plan_shards(self._sources, shard_bytes)
        n_workers = workers if workers else default_feeder_workers()
        self.workers = max(1, min(int(n_workers), max(1, len(self.shards))))
        self.batch_lines = int(batch_lines)
        self.line_len = int(line_len)
        self.queue_batches = max(1, int(queue_batches))
        self._requested_transport = transport
        self.ring_slots = (
            max(2, int(ring_slots)) if ring_slots
            else self.queue_batches + 2
        )
        # Default slot: room for batch_lines lines at a generous L plus
        # the raw payload — a batch that still doesn't fit (pathological
        # line bucket) ships pickled, so this is a fast path size, not a
        # correctness bound.
        self.slot_bytes = (
            int(slot_bytes) if slot_bytes
            else max(1 << 20, self.batch_lines * 768)
        )
        self._use_processes = use_processes
        self._mp_context = mp_context
        self._worker_delay_s = float(worker_delay_s)
        self._supervise = bool(supervise)
        self.policy = policy or SupervisorPolicy()
        self._chaos_arg = chaos
        self._chaos_spec: Any = None
        self._shutdown_timeout_s = float(shutdown_timeout_s)
        # Whether this pool feeds the process-wide queue_backpressure()
        # admission signal.  A STANDING ingest pool (the fabric keeping
        # chips fed) should; a short-lived per-request framing pool (the
        # service's _feeder_parse) must NOT — its queue sitting full for
        # the length of one request is the healthy steady state of that
        # request, not overload, and exporting it would shed every
        # concurrent request whenever one feeder-framed request runs.
        self._backpressure_signal = bool(backpressure_signal)
        self.mode: Optional[str] = None  # "process" | "thread" once started
        self.transport: Optional[str] = None  # resolved at start
        self.supervisor: Optional[FeederSupervisor] = None
        self._ctx: Any = None           # mp context (process mode)
        self._queues: List[Any] = []
        self._pumps: List[Any] = []     # per-worker _QueuePump (process mode)
        self._procs: List[Any] = []
        self._rings: List[Any] = []
        self._puts: List[Any] = []      # shared put-counters (process mode)
        self._gets: List[int] = []      # local get-counters (process mode)
        self._stops: List[Any] = []     # per-worker stop events
        self._started = False
        self._closed = False
        self._payload_cache: Optional[tuple] = None
        self._pending_quarantine: set = set()
        self._last_sweep = 0.0
        self._stats: Dict[str, Any] = {
            "shards": len(self.shards),
            "workers": self.workers,
            "batches": 0,
            "lines": 0,
            "payload_bytes": 0,
            "read_s": 0.0,
            "encode_s": 0.0,
            "starvation_s": 0.0,
            "startup_s": 0.0,
            "wall_s": 0.0,
            "queue_depth_max": 0,
            "queue_depth_mean": 0.0,
            "slot_wait_s": 0.0,
            "bytes_inplace": 0,
            "pickle_fallback_batches": 0,
            "batches_reframed": 0,
        }
        self._depth_samples = 0
        self._depth_sum = 0
        self._primed = False  # first item delivered (pipeline filled)

    # -- lifecycle -------------------------------------------------------

    def _start(self) -> None:
        if self._started:
            raise RuntimeError("FeederPool.batches() can only run once")
        self._started = True
        if self._backpressure_signal:
            _LIVE_POOLS.add(self)
        if self._chaos_arg is not None or os.environ.get(CHAOS_ENV, "").strip():
            from ..tools.chaos import ChaosSpec

            self._chaos_spec = (
                ChaosSpec.parse(self._chaos_arg)
                if isinstance(self._chaos_arg, str)
                else self._chaos_arg or ChaosSpec.from_env()
            )
        shards_of = [self._worker_plan(self.shards[w :: self.workers])
                     for w in range(self.workers)]
        if self._use_processes is not False:
            try:
                self._start_processes(shards_of)
            except Exception as e:  # noqa: BLE001 — environment-dependent
                if self._use_processes:
                    raise
                self._abort_process_start()
                log_warning_once(
                    LOG,
                    "feeder: multiprocessing unavailable "
                    f"({type(e).__name__}); falling back to threads",
                )
                self._start_threads(shards_of)
        else:
            self._start_threads(shards_of)
        if self._supervise:
            self.supervisor = FeederSupervisor(
                self.policy, self.workers, self.mode or "thread",
                self.transport or "inline",
            )

    def _abort_process_start(self) -> None:
        """Roll back a half-built process start before the thread
        fallback: unlink any arenas already created (they would
        otherwise sit in /dev/shm until interpreter exit) and clear the
        process-mode depth counters (stale ``_puts`` would make
        ``_queue_depth`` read 0 for the whole thread-mode run)."""
        for r in self._rings:
            if r is not None:
                r.close()
        self._rings = []
        self._puts = []
        self._gets = []
        for pump in self._pumps:
            if pump is not None:
                pump.retire()
        self._pumps = []
        self._queues = []
        self._procs = []
        self._stops = []
        self._ctx = None
        self.transport = None

    def _worker_plan(self, shards: List[Shard]):
        """(sources, shards) restricted to what ONE worker touches: its
        shard subset with source indices remapped into a filtered source
        list — spawned workers must not each receive a pickled copy of
        every in-memory blob in the pool (shard indices stay GLOBAL;
        only source references are localized)."""
        from dataclasses import replace

        used = sorted({s.source for s in shards})
        remap = {g: l for l, g in enumerate(used)}
        return (
            [self._sources[g] for g in used],
            [replace(s, source=remap[s.source]) for s in shards],
        )

    def _build_rings(self, queue_factory) -> List[Any]:
        """One arena per worker, free queues seeded; ``queue_factory``
        makes the free queues (ctx.Queue or queue.Queue)."""
        from .ring import SlotRing

        rings = []
        try:
            for w in range(self.workers):
                rings.append(SlotRing(
                    self.slot_bytes, self.ring_slots, queue_factory(),
                    name_hint=f"{os.getpid()}_{w}",
                ))
        except Exception:
            for r in rings:
                r.close()
            raise
        return rings

    def _queue_bound(self, transport: Optional[str]) -> int:
        # Queue bound by transport: for pickle it IS the backpressure —
        # exactly the documented queue_batches window.  For the ring,
        # slot exhaustion backpressures and the queue only carries small
        # descriptors (at most one per leased slot) plus control
        # messages — sized to never stall a slot-holding worker.
        return (self.ring_slots + 2 if transport == "ring"
                else self.queue_batches)

    def _start_processes(self, shards_of) -> None:
        import multiprocessing as mp

        method = self._mp_context
        if method is None:
            method = ("forkserver"
                      if "forkserver" in mp.get_all_start_methods()
                      else "spawn")
        ctx = mp.get_context(method)
        if method == "forkserver":
            # Preload the worker module graph (numpy included) into the
            # forkserver ONCE: children then fork with it already
            # imported, so worker (re)spawns cost milliseconds instead
            # of a full interpreter import — the difference between a
            # supervised respawn that retains throughput and one that
            # stalls the consumer for seconds mid-corpus.  No-op if the
            # forkserver is already running (first pool in the process
            # wins).
            try:
                ctx.set_forkserver_preload(
                    ["logparser_tpu.feeder.worker"]
                )
            except Exception:  # noqa: BLE001 — best-effort fast path
                pass
        self._ctx = ctx
        self.transport = resolve_transport(self._requested_transport,
                                           "process")
        if self.transport == "ring":
            try:
                self._rings = self._build_rings(ctx.Queue)
            except Exception as e:  # noqa: BLE001 — no /dev/shm etc.
                log_warning_once(
                    LOG,
                    "feeder: shared-memory ring unavailable "
                    f"({type(e).__name__}); falling back to pickle",
                )
                self.transport = "pickle"
        q_bound = self._queue_bound(self.transport)
        self._queues = [ctx.Queue(maxsize=q_bound)
                        for _ in range(self.workers)]
        self._puts = [ctx.Value("l", 0) for _ in range(self.workers)]
        self._gets = [0] * self.workers
        self._stops = [ctx.Event() for _ in range(self.workers)]
        procs = []
        try:
            for w in range(self.workers):
                w_sources, w_shards = shards_of[w]
                p = ctx.Process(
                    target=run_worker,
                    args=(w, w_sources, w_shards, self._queues[w],
                          self.batch_lines, self.line_len, self._stops[w],
                          self._worker_delay_s,
                          self._rings[w].spec() if self._rings else None,
                          self._puts[w], True, None, self._chaos_spec),
                    name=f"logparser-tpu-feeder-{w}",
                    daemon=True,
                )
                p.start()
                procs.append(p)
        except Exception:
            for p in procs:
                try:
                    p.terminate()
                except Exception as e:  # noqa: BLE001 — rollback best-effort
                    note_teardown_error(LOG, "start.terminate", e)
            raise
        self._procs = procs
        # Pumps last: nothing to retire if anything above raised.
        self._pumps = [_QueuePump(q, str(w))
                       for w, q in enumerate(self._queues)]
        self.mode = "process"

    def _start_threads(self, shards_of) -> None:
        self.transport = resolve_transport(self._requested_transport,
                                           "thread")
        writers: List[Any] = [None] * self.workers
        if self.transport == "ring":
            try:
                self._rings = self._build_rings(_queue.Queue)
                from .ring import SlotWriter

                writers = [SlotWriter(r.spec(), shm=r.shm)
                           for r in self._rings]
            except Exception as e:  # noqa: BLE001
                log_warning_once(
                    LOG,
                    "feeder: shared-memory ring unavailable "
                    f"({type(e).__name__}); falling back to inline",
                )
                self.transport = "inline"
        q_bound = self._queue_bound(self.transport)
        raw = [_queue.Queue(maxsize=q_bound)
               for _ in range(self.workers)]
        # Producer-side gauge updates: only possible in-process.
        self._queues = raw
        instrumented = [
            make_instrumented_queue(q, self._publish_depth) for q in raw
        ]
        self._stops = [threading.Event() for _ in range(self.workers)]
        self._procs = []
        for w in range(self.workers):
            w_sources, w_shards = shards_of[w]
            t = threading.Thread(
                target=run_worker,
                args=(w, w_sources, w_shards, instrumented[w],
                      self.batch_lines, self.line_len, self._stops[w],
                      self._worker_delay_s, writers[w], None, False,
                      None, self._chaos_spec),
                name=f"logparser-tpu-feeder-{w}",
                daemon=True,
            )
            t.start()
            self._procs.append(t)
        self._pumps = [None] * self.workers  # queue.Queue gets are atomic
        self.mode = "thread"

    # -- recovery: reap / respawn / quarantine ---------------------------

    def _join_escalate(self, p, timeout: float) -> None:
        """join -> terminate -> kill: a worker that ignores SIGTERM (or
        cannot receive it — SIGSTOPped) must not hang close() or a
        respawn; SIGKILL reaches even a stopped process.  Threads can
        only be joined (daemon threads die with the process)."""
        p.join(timeout=timeout)
        if not hasattr(p, "terminate") or not p.is_alive():
            return
        try:
            p.terminate()
        except Exception as e:  # noqa: BLE001
            note_teardown_error(LOG, "worker.terminate", e)
        p.join(timeout=timeout)
        if p.is_alive() and hasattr(p, "kill"):
            try:
                p.kill()
            except Exception as e:  # noqa: BLE001
                note_teardown_error(LOG, "worker.kill", e)
            p.join(timeout=timeout)

    def _reap_worker(self, worker: int) -> None:
        """Make sure worker ``worker``'s old incarnation is gone and its
        transport lane is retired: stale in-flight messages are
        discarded (the respawn replays them deterministically), the old
        queue is dropped, and a ring arena is closed — the respawn gets
        a FRESH ring, so slots leaked by the dead incarnation (acquired
        but never shipped) can't shrink the new one's capacity."""
        self._stops[worker].set()
        q = self._queues[worker]
        pump = self._pumps[worker] if self._pumps else None
        if pump is not None:
            # Drain the pump's buffer only: a get on the mp queue itself
            # (even get_nowait) can block in recv_bytes on a partial
            # frame from a mid-write death — the very hazard the pump
            # isolates.  Whatever is still in the pipe dies with the
            # retired queue.
            pump.retire()
            q = pump
        try:
            while True:
                q.get_nowait() if hasattr(q, "get_nowait") else q.get(
                    timeout=0
                )
        except _queue.Empty:
            pass
        except Exception as e:  # noqa: BLE001
            note_teardown_error(LOG, "reap.drain", e)
        q = self._queues[worker]
        p = self._procs[worker]
        if hasattr(p, "terminate"):
            self._join_escalate(p, min(1.0, self._shutdown_timeout_s))
        else:
            # A wedged thread cannot be killed: abandon it (its stop
            # event is set, its queue is retired — it exits at its next
            # put/acquire poll, daemon either way).
            p.join(timeout=0.2)
        if hasattr(q, "cancel_join_thread"):
            q.cancel_join_thread()
        if self._rings and self._rings[worker] is not None:
            self._rings[worker].close()
            self._rings[worker] = None

    def _respawn_worker(self, worker: int, transport: str,
                        shards: List[Shard],
                        resume: Optional[Dict[int, int]]) -> None:
        """Start a fresh incarnation of worker ``worker`` over
        ``shards`` (its remaining subset), on ``transport`` — possibly a
        rung below the pool's (``"inline"`` = a thread in the consumer
        process, even for process pools).  ``resume`` maps the in-flight
        shard to its replay skip count."""
        w_sources, w_shards = self._worker_plan(shards)
        chaos = (self._chaos_spec.respawn_view()
                 if self._chaos_spec is not None else None)
        as_process = self.mode == "process" and transport != "inline"
        ring = None
        if transport == "ring":
            from .ring import SlotRing

            try:
                ring = SlotRing(
                    self.slot_bytes, self.ring_slots,
                    self._ctx.Queue() if as_process else _queue.Queue(),
                    name_hint=f"{os.getpid()}_{worker}r",
                    prefault=False,  # mid-run rebuild: fault lazily
                )
            except Exception as e:  # noqa: BLE001 — arena gone mid-run
                log_warning_once(
                    LOG,
                    "feeder: ring rebuild failed on respawn "
                    f"({type(e).__name__}); worker continues on pickle",
                )
                transport = "pickle" if as_process else "inline"
                if self.supervisor is not None:
                    self.supervisor.transport_of[worker] = transport
        if self._rings:
            self._rings[worker] = ring
        q_bound = self._queue_bound(transport)
        if as_process:
            ctx = self._ctx
            q = ctx.Queue(maxsize=q_bound)
            stop = ctx.Event()
            puts = ctx.Value("l", 0)
            p = ctx.Process(
                target=run_worker,
                args=(worker, w_sources, w_shards, q, self.batch_lines,
                      self.line_len, stop, self._worker_delay_s,
                      ring.spec() if ring is not None else None,
                      puts, True, resume, chaos),
                name=f"logparser-tpu-feeder-{worker}",
                daemon=True,
            )
            p.start()
        else:
            q = _queue.Queue(maxsize=q_bound)
            stop = threading.Event()
            puts = None
            writer = None
            if ring is not None:
                from .ring import SlotWriter

                writer = SlotWriter(ring.spec(), shm=ring.shm)
            out_q = (make_instrumented_queue(q, self._publish_depth)
                     if self.mode == "thread" else q)
            p = threading.Thread(
                target=run_worker,
                args=(worker, w_sources, w_shards, out_q, self.batch_lines,
                      self.line_len, stop, self._worker_delay_s, writer,
                      None, False, resume, chaos),
                name=f"logparser-tpu-feeder-{worker}",
                daemon=True,
            )
            p.start()
        self._queues[worker] = q
        if self._pumps:
            self._pumps[worker] = (_QueuePump(q, f"{worker}r")
                                   if as_process else None)
        self._stops[worker] = stop
        self._procs[worker] = p
        if self._puts:
            self._puts[worker] = puts
            self._gets[worker] = 0

    def _shard_payload(self, shard: Shard) -> bytes:
        """The shard's healed payload, read in-process (quarantine and
        per-batch re-frame paths); cached per shard — ring-fault
        recovery may re-frame several batches of one shard."""
        if self._payload_cache and self._payload_cache[0] == shard.index:
            return self._payload_cache[1]
        payload = read_shard_payload(self._sources[shard.source], shard)
        self._payload_cache = (shard.index, payload)
        return payload

    def _frame_inproc(self, shard: Shard, index: int,
                      payload: bytes, ranges) -> EncodedBatch:
        """Frame batch ``index`` of ``shard`` in-process over the HOST
        (numpy) framer — byte-identical semantics to the native framer
        (the differential suite pins `_encode_blob_numpy` to it), but
        immune to whatever killed the worker, native framer included."""
        from ..native import _encode_blob_numpy

        p0, p1 = ranges[index]
        chunk = payload[p0:p1]
        t0 = time.perf_counter()
        buf, lengths, overflow = _encode_blob_numpy(
            chunk, self.line_len, 64, 8191, None
        )
        return EncodedBatch(
            shard=shard.index,
            index=index,
            payload=chunk,
            buf=buf,
            lengths=lengths,
            overflow=list(overflow),
            n_lines=int(buf.shape[0]) if len(chunk) else 0,
            encode_s=time.perf_counter() - t0,
        )

    def _reframe_batch(self, shard: Shard, index: int) -> EncodedBatch:
        """Recover ONE batch whose ring descriptor failed validation:
        delivery is ordered, so the next batch of the current shard is
        unambiguous regardless of what the corrupt descriptor claimed."""
        payload = self._shard_payload(shard)
        ranges = split_batches(payload, self.batch_lines)
        if index >= len(ranges):
            raise FeederError(
                f"shard {shard.index}: ring fault past the shard's last "
                f"batch (index {index} of {len(ranges)})"
            )
        self._stats["batches_reframed"] += 1
        return self._frame_inproc(shard, index, payload, ranges)

    def _quarantine_batches(
        self, shard: Shard, skip: int
    ) -> Iterator[EncodedBatch]:
        """The quarantine path: the rest of a poison shard, framed
        in-process from the last delivered batch boundary.  Raises
        FeederError only when the shard cannot even be read/framed in
        this process — the one case that still aborts a supervised run."""
        try:
            payload = self._shard_payload(shard)
            ranges = split_batches(payload, self.batch_lines)
            for bi in range(skip, len(ranges)):
                yield self._frame_inproc(shard, bi, payload, ranges)
        except FeederError:
            raise
        except Exception as e:
            raise FeederError(
                f"quarantined shard {shard.index} failed in-process too "
                f"({type(e).__name__}: {e}); the shard is unprocessable"
            ) from e

    def _owed_shards(self, worker: int, from_index: int,
                     inclusive: bool = True) -> List[Shard]:
        """The shards worker ``worker`` still owes the stream, at/after
        (``inclusive``) or strictly after ``from_index`` — the single
        home of the index-modulo ownership invariant every recovery
        path replays against."""
        lo = from_index if inclusive else from_index + 1
        return [s for s in self.shards
                if s.index % self.workers == worker and s.index >= lo]

    def _execute_decision(self, worker: int, decision, shards: List[Shard],
                          resume: Optional[Dict[int, int]], *,
                          backoff: bool = False, t0: float = 0.0) -> None:
        """The common mechanics of every supervised recovery: reap the
        old incarnation, count a transport demotion, optionally honor
        the decision's backoff, respawn over ``shards`` (skipped when
        the worker owes nothing) with ``resume`` replay and count the
        restart, then account recovery wall.  The three recovery paths
        (reactive fault, proactive sweep, ring demotion) differ only in
        the shard set / replay map / backoff they pass."""
        reg = metrics()
        self._reap_worker(worker)
        if decision.demoted_from:
            reg.increment(
                "feeder_transport_demotions_total",
                labels={"from": decision.demoted_from,
                        "to": decision.transport},
            )
        if backoff and decision.backoff_s:
            time.sleep(decision.backoff_s)
        if shards:
            self._respawn_worker(worker, decision.transport, shards, resume)
            reg.increment("feeder_worker_restarts_total")
            self.supervisor.total_restarts += 1
        self.supervisor.recovery_s += time.perf_counter() - t0

    def _handle_worker_fault(self, worker: int, shard: Shard,
                             delivered: int, fault: WorkerFault) -> str:
        """One dead/errored/stalled/protocol-breaking worker while
        ``shard`` was draining.  Unsupervised: the historical fail-stop
        FeederError.  Supervised: execute the supervisor's Decision —
        reap, then respawn with replay, or quarantine.  Returns the
        action taken ("respawned" | "quarantine")."""
        if self.supervisor is None:
            if fault.kind == "error":
                raise FeederError(
                    f"feeder worker {worker} failed:\n{fault.detail}"
                ) from None
            if fault.kind == "protocol":
                raise FeederError(
                    f"feeder protocol violation: {fault.detail}"
                ) from None
            if fault.kind == "stalled":
                raise FeederError(
                    f"feeder worker {worker} stalled past the "
                    f"{self.policy.worker_deadline_s}s deadline"
                ) from None
            raise FeederError(
                f"feeder worker {worker} exited without completing its "
                "shards"
            ) from None
        t0 = time.perf_counter()
        decision = self.supervisor.on_worker_fault(worker, shard.index)
        log_warning_once(
            LOG,
            f"feeder: worker {worker} fault ({fault.kind}) on shard "
            f"{shard.index}; supervised recovery: {decision.action}"
            + (f" (transport {decision.demoted_from} -> "
               f"{decision.transport})" if decision.demoted_from else ""),
        )
        if decision.action == "quarantine":
            self._note_quarantine(shard)
            remaining = self._owed_shards(worker, shard.index,
                                          inclusive=False)
            self._execute_decision(worker, decision, remaining, None, t0=t0)
            return "quarantine"
        remaining = self._owed_shards(worker, shard.index)
        self._execute_decision(worker, decision, remaining,
                               {shard.index: delivered}, backoff=True, t0=t0)
        metrics().increment("feeder_shards_requeued_total")
        return "respawned"

    def _note_quarantine(self, shard: Shard) -> None:
        self.supervisor.quarantined.append(shard.index)
        metrics().increment("feeder_shards_quarantined_total")

    def _sweep_dead_workers(self, current_worker: int) -> None:
        """Proactive supervision: while the consumer idles on the
        CURRENT worker's queue, look for OTHER workers that died early
        (dead, queue empty, shards still owed) and respawn them NOW —
        by the time the consumer reaches their shards, the replacement
        is already framing, so recovery wall overlaps delivery instead
        of serializing behind it.  Throttled; skipped where queue sizes
        are unobservable (the positional path still catches everything,
        just later)."""
        sup = self.supervisor
        if sup is None:
            return
        now = time.monotonic()
        if now - self._last_sweep < 0.05:
            return
        self._last_sweep = now
        current_index = getattr(self, "_current_shard_index", -1)
        for w in range(self.workers):
            if w == current_worker or self._procs[w].is_alive():
                continue
            try:
                buffered = self._queues[w].qsize()
            except (NotImplementedError, OSError):
                continue  # no qsize: leave it to positional detection
            pump = self._pumps[w] if self._pumps else None
            if pump is not None:
                buffered += pump.qsize()
            if buffered > 0:
                continue  # buffered work first; recheck next sweep
            owed = self._owed_shards(w, current_index, inclusive=False)
            if not owed:
                continue  # finished everything it owed: a normal exit
            t0 = time.perf_counter()
            decision = sup.on_worker_fault(w, owed[0].index)
            log_warning_once(
                LOG,
                f"feeder: worker {w} found dead ahead of its shards; "
                f"proactive {decision.action}",
            )
            respawn_shards = owed
            if decision.action == "quarantine":
                # Executed when the consumer reaches the shard (the
                # in-process re-frame must interleave at its ordered
                # position); the replacement skips it.
                self._pending_quarantine.add(owed[0].index)
                self._note_quarantine(owed[0])
                respawn_shards = owed[1:]
            # No backoff on the proactive path: the death already aged
            # while the consumer was busy elsewhere, and a crash loop
            # stays bounded by the restart budget.
            self._execute_decision(w, decision, respawn_shards, None, t0=t0)

    def _apply_demotion(self, worker: int, shard: Shard,
                        next_index: int, decision) -> None:
        """Execute a ring-lane demotion Decision: reap the (healthy but
        ring-compromised) worker and respawn it one rung down, replaying
        the current shard from ``next_index``."""
        log_warning_once(
            LOG,
            f"feeder: worker {worker} demoted off the ring "
            f"({decision.demoted_from} -> {decision.transport}) after "
            "repeated ring faults",
        )
        t0 = time.perf_counter()
        remaining = self._owed_shards(worker, shard.index)
        self._execute_decision(worker, decision, remaining,
                               {shard.index: next_index}, t0=t0)

    def close(self) -> None:
        """Stop workers, drop queues, unlink ring arenas.  Idempotent;
        also runs on normal exhaustion of :meth:`batches`.  Worker
        shutdown escalates join -> terminate -> kill (a SIGSTOPped or
        SIGTERM-deaf worker cannot hang close()); teardown failures are
        warned once + counted (``feeder_teardown_errors_total``), never
        silently swallowed."""
        if self._closed:
            return
        self._closed = True
        _LIVE_POOLS.discard(self)
        for stop in self._stops:
            stop.set()
        # Drain so workers blocked on a full queue observe the stop event
        # promptly instead of at their next 0.1 s put timeout.  Pumped
        # (process) lanes drain the pump buffer only — touching the mp
        # queue directly risks the partial-frame recv_bytes hang.
        for w, q in enumerate(self._queues):
            pump = self._pumps[w] if self._pumps else None
            if pump is not None:
                pump.retire()
                q = pump
            try:
                while True:
                    q.get_nowait() if hasattr(q, "get_nowait") else q.get(
                        timeout=0
                    )
            except _queue.Empty:
                pass
            except Exception as e:  # noqa: BLE001
                note_teardown_error(LOG, "close.drain", e)
        for p in self._procs:
            self._join_escalate(p, self._shutdown_timeout_s)
        for q in self._queues:
            # mp.Queue feeder threads keep the process alive unless
            # cancelled; plain queue.Queue has no such method.
            if hasattr(q, "cancel_join_thread"):
                q.cancel_join_thread()
        for r in self._rings:
            if r is not None:
                r.close()
        metrics().gauge_set("feeder_queue_depth", 0)

    def __enter__(self) -> "FeederPool":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- metrics helpers -------------------------------------------------

    def _queue_depth(self) -> int:
        if self._puts:
            # Process mode: shared put-counters minus this consumer's get
            # counts — live on every platform (macOS mp queues have no
            # qsize) and unaffected by pipe buffering.  A worker demoted
            # to an inline thread has no shared counter (None hole) —
            # its plain queue.Queue has a working qsize instead.
            total = 0
            for w in range(self.workers):
                if self._puts[w] is not None:
                    total += max(0, self._puts[w].value - self._gets[w])
                else:
                    try:
                        total += self._queues[w].qsize()
                    except (NotImplementedError, OSError):
                        pass
            return total
        total = 0
        for q in self._queues:
            try:
                total += q.qsize()
            except (NotImplementedError, OSError):
                return -1  # platform without qsize (macOS mp queues)
        return total

    def backpressure(self) -> float:
        """THIS pool's queue occupancy as a 0.0–1.0 fraction of its
        REACHABLE capacity.  For the ring that is ``workers x
        ring_slots`` — a saturated worker can have at most one
        descriptor per leased slot outstanding, so dividing by the
        descriptor-queue bound (``ring_slots + 2`` control slack) would
        cap the fraction at ~0.75 and a fully wedged fabric could never
        cross a 0.95 shed threshold.  For pickle/inline lanes the
        bounded queue itself is the capacity.  0.0 before start, after
        close, or on a platform where depth is unknowable — unknown
        must read as "admit", never as "shed".  The process-wide
        aggregate is :func:`queue_backpressure`."""
        if not self._started or self._closed:
            return 0.0
        depth = self._queue_depth()
        if depth < 0:
            return 0.0
        per_worker = (self.ring_slots if self.transport == "ring"
                      else self._queue_bound(self.transport))
        cap = self.workers * per_worker
        if cap <= 0:
            return 0.0
        return min(1.0, depth / cap)

    def _publish_depth(self) -> None:
        depth = self._queue_depth()
        if depth >= 0:
            metrics().gauge_set("feeder_queue_depth", depth)

    def _sample_depth(self) -> None:
        depth = self._queue_depth()
        if depth < 0:
            return
        metrics().gauge_set("feeder_queue_depth", depth)
        self._depth_samples += 1
        self._depth_sum += depth
        if depth > self._stats["queue_depth_max"]:
            self._stats["queue_depth_max"] = depth

    # -- consumption -----------------------------------------------------

    def _lane(self, worker: int):
        """The consumer-facing end of worker ``worker``'s message lane:
        its :class:`_QueuePump` for process workers (recv_bytes hazard
        isolation), the queue itself for thread/inline workers."""
        pump = self._pumps[worker] if self._pumps else None
        return pump if pump is not None else self._queues[worker]

    def _get(self, q, worker: int):
        """Blocking dequeue that accounts starvation and watches THIS
        queue's producer: a dead producer (crash/os._exit/silent thread
        return) raises WorkerFault("died") once its queue is empty, and
        an ALIVE but silent producer raises WorkerFault("stalled") past
        the policy's worker deadline (when one is set) — in both cases
        the supervised pool recovers; unsupervised, FeederError."""
        deadline = self.policy.worker_deadline_s
        t_enter = time.perf_counter()
        blocked = 0.0  # time spent in Empty waits only — a successful
        # get's own duration (pipe read + unpickling of a multi-MB
        # batch in pickle mode) is transfer, not starvation.
        while True:
            t0 = time.perf_counter()
            try:
                # Short poll: blocked time is only observable in whole
                # Empty windows, so the window is the accounting grain.
                msg = q.get(timeout=0.05)
                break
            except _queue.Empty:
                blocked += time.perf_counter() - t0
                if not self._procs[worker].is_alive():
                    # Producer gone with its queue empty: it died before
                    # reporting (e.g. SIGKILL).  One grace re-read in
                    # case its final messages were still in flight (a
                    # complete message already in the pipe reads back
                    # immediately; a partial pickle never completes, so
                    # a short timeout is the only thing that tells the
                    # two apart).
                    try:
                        msg = q.get(timeout=0.15)
                        break
                    except _queue.Empty:
                        raise WorkerFault("died", worker) from None
                if deadline is not None and blocked >= deadline:
                    raise WorkerFault(
                        "stalled", worker,
                        f"no output for {blocked:.1f}s "
                        f"(deadline {deadline}s)",
                    ) from None
                # The consumer is idle anyway: use the window to find
                # (and revive) dead NON-current workers before their
                # shards come up — recovery overlaps delivery instead
                # of serializing behind it.
                self._sweep_dead_workers(worker)
        if self._gets:
            self._gets[worker] += 1
        if not self._primed:
            # Pipeline fill — worker start, first read/frame AND the
            # first item's queue transfer — is startup latency, not
            # starvation: the chip wasn't waiting on a fabric that had
            # ever been ahead of it.  Post-prime gets only ever count
            # their Empty windows (the transfer itself is throughput).
            self._primed = True
            self._stats["startup_s"] = time.perf_counter() - t_enter
        elif blocked > 0:
            self._stats["starvation_s"] += blocked
            metrics().increment("feeder_starvation_seconds_total", blocked)
        self._sample_depth()
        return msg

    def _account_batch(self, eb: EncodedBatch) -> None:
        """Volume/stage accounting for one delivered batch — identical
        for worker-framed, re-framed and quarantined batches (recovered
        runs must report the same totals as undisturbed ones)."""
        reg = metrics()
        self._stats["batches"] += 1
        self._stats["lines"] += eb.n_lines
        self._stats["payload_bytes"] += eb.source_bytes
        self._stats["read_s"] += eb.read_s
        self._stats["encode_s"] += eb.encode_s
        reg.increment("feeder_bytes_read_total", eb.source_bytes)
        reg.increment("feeder_lines_total", eb.n_lines)
        reg.increment("feeder_batches_total")
        observe_stage("feeder_read", eb.read_s, items=eb.n_lines)
        observe_stage("feeder_encode", eb.encode_s, items=eb.n_lines)

    def batches(self, detach: bool = True) -> Iterator[EncodedBatch]:
        """The ordered batch stream (single use).  Yields every framed
        batch of every shard, in global shard order, then joins the
        workers and closes the pool.

        ``detach=True`` (default): ring batches are converted to owned
        copies and their slots released immediately — hold as many as
        you like.  ``detach=False``: ring batches arrive as ZERO-COPY
        slot views; the caller must ``release()`` each one (or the ring
        exhausts and the producers block) and must not touch a batch
        after releasing it.  ``feed()`` uses the zero-copy flavor with
        ``parse_batch_stream`` handling the releases.

        Under supervision (the default) the stream is FAULT-TRANSPARENT:
        worker deaths, stalls, ring faults and poison shards are
        recovered behind this iterator (replay is deterministic, so the
        delivered byte stream is identical to an undisturbed run's);
        only an in-process quarantine failure raises FeederError."""
        self._start()
        reg = metrics()
        sup = self.supervisor
        t_start = time.perf_counter()
        try:
            for shard in self.shards:
                worker = shard.index % self.workers
                self._current_shard_index = shard.index
                delivered = 0  # batches of THIS shard yielded so far
                quarantined = shard.index in self._pending_quarantine
                if quarantined:
                    # Decided by a proactive sweep (counters already
                    # bumped there); executed here, at stream order.
                    self._pending_quarantine.discard(shard.index)
                while not quarantined:
                    try:
                        msg = self._get(self._lane(worker), worker)
                    except WorkerFault as fault:
                        if self._handle_worker_fault(
                            worker, shard, delivered, fault
                        ) == "quarantine":
                            quarantined = True
                            break
                        continue  # respawned onto a fresh queue: re-get
                    kind = msg[0]
                    if kind == MSG_SLOT:
                        desc = msg[1]
                        ring = (self._rings[worker] if self._rings
                                else None)
                        demote = None
                        try:
                            if ring is None:
                                raise RingFault(
                                    "descriptor",
                                    "slot descriptor from a worker with "
                                    "no ring",
                                )
                            eb: EncodedBatch = ring.map(desc)
                        except RingFault as rf:
                            if sup is None:
                                raise FeederError(
                                    f"feeder worker {worker}: {rf}"
                                ) from rf
                            reg.increment(
                                "feeder_ring_generation_mismatch_total"
                                if rf.reason == "generation"
                                else "feeder_ring_descriptor_faults_total"
                            )
                            if rf.stale:
                                # A replay of a send already mapped and
                                # delivered: re-framing would duplicate
                                # the batch in the stream, and the slot
                                # belongs to whoever legitimately holds
                                # its lease now — drop the descriptor
                                # (still a ring fault for the demotion
                                # ledger; resume stays at `delivered`:
                                # nothing was yielded).
                                log_warning_once(
                                    LOG,
                                    f"feeder: worker {worker} {rf}; "
                                    "stale duplicate dropped",
                                )
                                demote = sup.on_ring_fault(worker)
                                if demote is not None:
                                    self._apply_demotion(
                                        worker, shard, delivered, demote
                                    )
                                continue
                            log_warning_once(
                                LOG,
                                f"feeder: worker {worker} {rf}; batch "
                                "re-framed in-process",
                            )
                            if (ring is not None
                                    and 0 <= desc.slot < ring.n_slots):
                                # The worker holds a lease on this
                                # bounds-valid slot even though the
                                # descriptor failed validation: return
                                # it, or every sub-threshold fault
                                # shrinks the arena by one slot until
                                # producer (acquire) and consumer
                                # (empty queue) deadlock.
                                ring.release(desc.slot)
                            eb = self._reframe_batch(shard, delivered)
                            demote = sup.on_ring_fault(worker)
                        else:
                            reg.increment(
                                "feeder_ring_slot_wait_seconds_total",
                                desc.slot_wait_s,
                            )
                            inplace = ring.inplace_bytes(desc)
                            reg.increment("feeder_ring_bytes_inplace_total",
                                          inplace)
                            self._stats["slot_wait_s"] += desc.slot_wait_s
                            self._stats["bytes_inplace"] += inplace
                        if demote is not None:
                            # Kill + respawn one rung down BEFORE the
                            # yield: the new incarnation replays from
                            # the batch after this (re-framed) one.
                            self._apply_demotion(worker, shard,
                                                 delivered + 1, demote)
                    elif kind == MSG_BATCH:
                        eb = msg[1]
                        worker_transport = (sup.transport_of[worker]
                                            if sup else self.transport)
                        if worker_transport == "ring":
                            # Slot-overflow fallback batch (counted, not
                            # fatal: the ring degrades per batch).  Its
                            # slot-acquire wait still happened — keep the
                            # backpressure signal honest under overflow.
                            self._stats["pickle_fallback_batches"] += 1
                            reg.increment("feeder_ring_pickle_fallback_total")
                            self._stats["slot_wait_s"] += eb.slot_wait_s
                            reg.increment(
                                "feeder_ring_slot_wait_seconds_total",
                                eb.slot_wait_s,
                            )
                            demote = (sup.on_overflow_fallback(worker)
                                      if sup else None)
                            if demote is not None:
                                # An overflow STORM: the ring is mis-
                                # sized for this corpus — move the
                                # worker off it (batch in hand is fine).
                                self._apply_demotion(worker, shard,
                                                     delivered + 1, demote)
                    elif kind == MSG_SHARD_DONE:
                        _, sidx, wall_s, n_lines, _nbytes = msg
                        if sidx != shard.index:
                            fault = WorkerFault(
                                "protocol", worker,
                                f"shard_done for {sidx} while draining "
                                f"{shard.index}",
                            )
                            if self._handle_worker_fault(
                                worker, shard, delivered, fault
                            ) == "quarantine":
                                quarantined = True
                            continue
                        reg.increment("feeder_shards_total")
                        observe_stage("feeder_shard", wall_s, items=n_lines)
                        break
                    elif kind == MSG_ERROR:
                        fault = WorkerFault("error", msg[1], msg[2])
                        if self._handle_worker_fault(
                            worker, shard, delivered, fault
                        ) == "quarantine":
                            quarantined = True
                        continue
                    else:  # MSG_DONE out of order: worker finished early
                        fault = WorkerFault(
                            "protocol", worker,
                            f"{kind!r} before shard {shard.index} "
                            "completed",
                        )
                        if self._handle_worker_fault(
                            worker, shard, delivered, fault
                        ) == "quarantine":
                            quarantined = True
                        continue
                    if eb.shard != shard.index:
                        fault = WorkerFault(
                            "protocol", worker,
                            f"got shard {eb.shard}, expected "
                            f"{shard.index}",
                        )
                        eb.release()
                        if self._handle_worker_fault(
                            worker, shard, delivered, fault
                        ) == "quarantine":
                            quarantined = True
                        continue
                    self._account_batch(eb)
                    yield eb.detach() if detach else eb
                    delivered += 1
                if quarantined:
                    reg.increment("feeder_shards_total")
                    for eb in self._quarantine_batches(shard, delivered):
                        self._account_batch(eb)
                        yield eb
        finally:
            self._stats["wall_s"] = time.perf_counter() - t_start
            if self._depth_samples:
                self._stats["queue_depth_mean"] = round(
                    self._depth_sum / self._depth_samples, 3
                )
            self.close()

    def feed(self, parser, emit_views: Optional[bool] = None, depth: int = 1):
        """Drive ``parser`` (a TpuBatchParser) over the batch stream:
        yields one BatchResult per batch, in corpus order, with the
        host-side stages of batch k overlapping the device work of batch
        k+1 (``parse_batch_stream`` semantics).  Ring batches flow
        through ZERO-COPY: the stream stages each batch's H2D upload
        straight from (a bucket-padded adoption of) the slot frame and
        releases the slot once the batch materializes — after device
        upload and rescue-payload use."""
        return parser.parse_batch_stream(
            self.batches(detach=False), depth=depth, emit_views=emit_views
        )

    def stats(self) -> Dict[str, Any]:
        """Post-run (or mid-run) feed accounting.  Rates and the
        starvation fraction are computed over the STEADY window (wall
        minus pipeline-fill startup): the one-time worker start + first
        read/frame latency is reported as ``startup_s`` instead of
        polluting the sustained numbers.  ``slot_wait_fraction`` is the
        ring backpressure share: total worker slot-wait over the steady
        window summed across workers (1.0 = every worker blocked the
        whole time = the consumer is the bottleneck).  Supervised pools
        add the recovery ledger (restarts, quarantines, demotions, ring
        faults, recovery wall)."""
        out = dict(self._stats)
        out["mode"] = self.mode
        out["transport"] = self.transport
        out["ring_slots"] = self.ring_slots
        if self.supervisor is not None:
            out.update(self.supervisor.summary())
        else:
            out.update({"worker_restarts": 0, "shards_quarantined": 0,
                        "transport_demotions": 0, "ring_faults": 0,
                        "recovery_s": 0.0})
        steady = out["wall_s"] - out["startup_s"]
        if steady > 0:
            out["bytes_per_sec"] = round(out["payload_bytes"] / steady, 1)
            out["starvation_fraction"] = round(
                out["starvation_s"] / steady, 4
            )
            out["slot_wait_fraction"] = round(
                out["slot_wait_s"] / (steady * max(1, self.workers)), 4
            )
        return out
