"""CLI for the durable job runner: ``python -m logparser_tpu.jobs``.

Examples::

    # parse a corpus into sharded Arrow files (resumable by default)
    python -m logparser_tpu.jobs access.log \\
        --format '%h %l %u %t "%r" %>s %b' \\
        --field IP:connection.client.host \\
        --field STRING:request.status.last \\
        --out /data/job1

    # after a crash: the same command resumes from the manifest,
    # skipping committed shards

Exit codes: 0 = job complete; 1 = one or more shards failed durably
(resume retries them); 2 = configuration error (manifest mismatch,
bad arguments); 3 = preempted — SIGTERM (the cloud-TPU preemption
notice) was honored at a shard commit boundary: the manifest resumes
exactly, an orchestrator should simply relaunch the same command
(docs/JOBS.md "Preemption").
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from .manifest import ManifestError, merge_manifests
from .writer import merged_job_aggregate
from .runner import (
    DEFAULT_JOB_BATCH_LINES,
    EXIT_PREEMPTED,
    JobPolicy,
    JobSpec,
    run_job,
)
from ..feeder.shards import DEFAULT_SHARD_BYTES


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m logparser_tpu.jobs",
        description="Durable corpus -> sharded-Arrow parse job "
                    "(docs/JOBS.md)",
    )
    ap.add_argument("sources", nargs="+",
                    help="input log files, in corpus order")
    ap.add_argument("--format", required=True, dest="log_format",
                    help="the Apache/NGINX LogFormat string")
    ap.add_argument("--field", action="append", required=True,
                    dest="fields", metavar="TYPE:path",
                    help="requested field id (repeatable)")
    ap.add_argument("--out", required=True, dest="out_dir",
                    help="job output directory (manifest + shard files)")
    ap.add_argument("--shard-bytes", type=int,
                    default=DEFAULT_SHARD_BYTES)
    ap.add_argument("--batch-lines", type=int,
                    default=DEFAULT_JOB_BATCH_LINES)
    ap.add_argument("--workers", type=int, default=None,
                    help="feeder worker count (default: auto)")
    ap.add_argument("--threads", action="store_true",
                    help="thread feeder workers instead of processes")
    ap.add_argument("--transport", choices=("ring", "pickle", "inline"),
                    default=None)
    ap.add_argument("--no-resume", action="store_true",
                    help="refuse to continue an existing manifest "
                         "(default: resume it)")
    ap.add_argument("--io-retries", type=int, default=3)
    ap.add_argument("--hosts", type=int, default=1,
                    help="pod size: partition the shard plan over this "
                         "many hosts (docs/JOBS.md 'Pod jobs')")
    ap.add_argument("--host-index", type=int, default=0,
                    help="which pod host THIS run is (0-based; commits "
                         "into manifest.host-NNN.json)")
    ap.add_argument("--merge", action="store_true",
                    help="after this host's share completes, merge all "
                         "per-host manifests into manifest.json "
                         "(run standalone with --merge-only)")
    ap.add_argument("--merge-only", action="store_true",
                    help="only merge per-host manifests into "
                         "manifest.json; parse nothing")
    ap.add_argument("--data-parallel", type=int, default=None,
                    help="lay the device parse over N local chips "
                         "(jax.sharding mesh; default: single device)")
    ap.add_argument("--aggregate", default=None, metavar="JSON",
                    help="aggregate mode (docs/ANALYTICS.md): a JSON "
                         "list of aggregation ops; shards land partial-"
                         "aggregate sidecars instead of data tables and "
                         "the completed job prints the merged aggregate "
                         "summary")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent compile-cache directory "
                         "(docs/COMPILE.md): resumed/repeated jobs and "
                         "pod hosts deserialize cached executables "
                         "instead of recompiling "
                         "(= LOGPARSER_TPU_COMPILE_CACHE)")
    ap.add_argument("--stop-after-shards", type=int, default=None,
                    help=argparse.SUPPRESS)  # crash-drill hook (smoke)
    return ap


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.compile_cache:
        import os

        from ..tpu.compile_cache import ENV_CACHE_DIR

        os.environ[ENV_CACHE_DIR] = args.compile_cache
    # SIGTERM = the cloud-TPU preemption notice: finish/commit the
    # current shard boundary, exit EXIT_PREEMPTED (resumable — cheaper
    # than the SIGKILL path by exactly one replayed shard).  An
    # immediate stop is SIGKILL, which the manifest already survives
    # (docs/JOBS.md "Preemption").  The previous disposition is
    # restored on the way out — an embedding process must not keep
    # swallowing SIGTERM into a dead Event after main() returns.
    stop = threading.Event()
    try:
        prev_sigterm = signal.signal(
            signal.SIGTERM, lambda signum, frame: stop.set()
        )
    except ValueError:
        prev_sigterm = None  # not the main thread: no handler, no stop
    try:
        return _main(args, stop)
    finally:
        if prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, prev_sigterm)
            except (ValueError, TypeError):
                pass


def _main(args, stop) -> int:
    spec = JobSpec(
        sources=list(args.sources),
        log_format=args.log_format,
        fields=list(args.fields),
        out_dir=args.out_dir,
        shard_bytes=args.shard_bytes,
        batch_lines=args.batch_lines,
        workers=args.workers,
        use_processes=False if args.threads else None,
        transport=args.transport,
        n_hosts=args.hosts,
        host_index=args.host_index,
        data_parallel=args.data_parallel,
        aggregate=args.aggregate,
    )
    policy = JobPolicy(io_retries=args.io_retries,
                       stop_after_shards=args.stop_after_shards,
                       stop_event=stop)
    try:
        if args.merge_only:
            merged = merge_manifests(args.out_dir)
            d = {
                "out_dir": args.out_dir,
                "merged_shards": len(merged.shards),
            }
            if merged.job.get("aggregate"):
                d["aggregate"] = merged_job_aggregate(
                    args.out_dir, merged).summary()
            print(json.dumps(d))
            return 0
        report = run_job(spec, resume=not args.no_resume, policy=policy)
        if args.merge and report.complete:
            merged = merge_manifests(args.out_dir)
            d = report.as_dict()
            d["merged_shards"] = len(merged.shards)
            if args.aggregate:
                d["aggregate"] = merged_job_aggregate(
                    args.out_dir, merged).summary()
            print(json.dumps(d))
            return 0  # complete implies no failed shards
    except (ManifestError, ValueError) as e:
        print(json.dumps({"error": str(e)}), file=sys.stderr)
        return 2
    d = report.as_dict()
    if args.aggregate and args.hosts == 1 and report.complete:
        # Single-host aggregate job: the merged answer is ready — print
        # it (a pod host's share is partial; --merge owns that case).
        try:
            d["aggregate"] = merged_job_aggregate(args.out_dir).summary()
        except (OSError, ValueError) as e:
            print(json.dumps({"error": str(e)}), file=sys.stderr)
            return 2
    print(json.dumps(d))
    if report.failed:
        return 1
    return EXIT_PREEMPTED if report.preempted else 0


if __name__ == "__main__":
    sys.exit(main())
