"""Durable job manifest: the commit log of a corpus -> sharded-Arrow job.

The manifest is the SINGLE source of truth for what a job has durably
produced.  The commit protocol (docs/JOBS.md) is strictly ordered:

1. a shard's data/reject tables are written to ``*.tmp`` files,
   flushed, **fsync**\\ ed, then atomically **renamed** into place;
2. only then is the shard's :class:`ShardRecord` added to the manifest,
   which is itself rewritten atomically (tmp -> fsync -> rename, plus a
   directory fsync so the rename survives a power cut).

A shard therefore exists in exactly one of two states after ANY crash:
committed (recorded in the manifest, its files complete and hashed) or
not committed (absent from the manifest; any leftover ``*.tmp`` debris
or orphaned output file is overwritten deterministically on resume).
There is no third state — that is what makes ``resume()`` exactly-once:
committed shards are skipped wholesale, everything else replays from
the corpus, and replay is deterministic (same shard plan, same batch
splits, same parse), so the merged output is byte-identical to an
undisturbed run's.

The ``job`` fingerprint block pins everything that determines output
bytes (format, fields, sources, shard/batch geometry).  A resume
against a manifest whose fingerprint disagrees is REFUSED — silently
mixing two configurations' shards would corrupt the corpus without any
crash at all.

Everything here is stdlib-only (json/os/hashlib) and jax-free.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1


class ManifestError(RuntimeError):
    """The manifest is unreadable, structurally invalid, or belongs to a
    different job configuration than the one asking to resume."""


@dataclass
class ShardRecord:
    """One committed shard: identity (the GLOBAL plan index + raw byte
    range), volume, output files and their content hashes."""

    shard: int                 # global shard index in the job plan
    source: int                # index into the job's source list
    start: int                 # raw byte range (pre-healing)
    end: int
    lines: int                 # lines parsed (valid + rejected)
    rows: int                  # data rows written (valid lines)
    rejects: int               # reject-table rows
    payload_bytes: int         # healed payload bytes parsed
    data_file: Optional[str]   # relative filename; None when rows == 0
    reject_file: Optional[str]  # relative filename; None when rejects == 0
    data_hash: Optional[str]   # blake2b hex of the data file bytes
    reject_hash: Optional[str]
    committed_at: float = 0.0  # wall clock; NOT part of output identity

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ShardRecord":
        return cls(**{k: d.get(k) for k in cls.__dataclass_fields__})


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed entry survives a power cut
    (rename is atomic but not durable until the directory metadata is
    flushed).  Best-effort on filesystems that refuse O_RDONLY dir
    fsync (the rename is still atomic there)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp -> flush -> fsync -> rename -> dir fsync.  The reader either
    sees the whole previous version or the whole new one, never a
    torn write."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(path) or ".")


@dataclass
class JobManifest:
    """The on-disk commit log (see module docstring)."""

    job: Dict[str, Any]                      # the config fingerprint block
    shards: Dict[int, ShardRecord] = field(default_factory=dict)
    version: int = MANIFEST_VERSION
    created_at: float = 0.0

    # -- construction / io ----------------------------------------------

    @classmethod
    def fresh(cls, fingerprint: Dict[str, Any]) -> "JobManifest":
        return cls(job=dict(fingerprint), created_at=time.time())

    @classmethod
    def load(cls, out_dir: str) -> Optional["JobManifest"]:
        """The manifest of ``out_dir``, or None when none exists.
        Raises :class:`ManifestError` on a corrupt/foreign file — a
        half-written manifest cannot exist under the atomic-write
        protocol, so corruption means outside interference and must not
        be silently treated as 'no job here'."""
        path = os.path.join(out_dir, MANIFEST_NAME)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                raw = json.loads(f.read().decode("utf-8"))
            if raw.get("version") != MANIFEST_VERSION:
                raise ManifestError(
                    f"manifest version {raw.get('version')!r} != "
                    f"{MANIFEST_VERSION} (written by a different build?)"
                )
            shards = {
                int(k): ShardRecord.from_dict(v)
                for k, v in raw.get("shards", {}).items()
            }
            return cls(
                job=raw["job"], shards=shards,
                version=raw["version"],
                created_at=raw.get("created_at", 0.0),
            )
        except ManifestError:
            raise
        except Exception as e:  # noqa: BLE001 — corrupt json/schema
            raise ManifestError(
                f"unreadable manifest at {path}: {type(e).__name__}: {e}"
            ) from e

    def serialize(self) -> bytes:
        payload = {
            "version": self.version,
            "created_at": self.created_at,
            "job": self.job,
            "shards": {
                str(k): asdict(v) for k, v in sorted(self.shards.items())
            },
        }
        return json.dumps(payload, indent=1, sort_keys=True).encode("utf-8")

    def save(self, out_dir: str) -> None:
        atomic_write_bytes(
            os.path.join(out_dir, MANIFEST_NAME), self.serialize()
        )

    # -- commit log -----------------------------------------------------

    def commit(self, out_dir: str, record: ShardRecord,
               write_bytes=None) -> None:
        """Record one shard as durably written — THE single commit
        path.  The caller has already renamed the shard's files into
        place; once the manifest rewrite lands, resume skips the shard
        forever.  ``write_bytes(name, data)`` overrides the write (the
        job runner routes it through its retrying
        :class:`~logparser_tpu.jobs.writer.JobWriter`); on ANY write
        failure the record is rolled back out of the in-memory map so
        the manifest object still mirrors the disk truth."""
        record.committed_at = time.time()
        self.shards[record.shard] = record
        try:
            if write_bytes is not None:
                write_bytes(MANIFEST_NAME, self.serialize())
            else:
                self.save(out_dir)
        except BaseException:
            del self.shards[record.shard]
            raise

    def committed_indices(self) -> List[int]:
        return sorted(self.shards)

    # -- fingerprinting -------------------------------------------------

    def mismatch(self, fingerprint: Dict[str, Any]) -> Optional[str]:
        """None when ``fingerprint`` matches this manifest's job block;
        otherwise a human-readable description of the first divergence
        (the resume refusal message)."""
        for key in sorted(set(self.job) | set(fingerprint)):
            a, b = self.job.get(key), fingerprint.get(key)
            if a != b:
                return f"{key}: manifest has {a!r}, job has {b!r}"
        return None
