"""Durable job manifest: the commit log of a corpus -> sharded-Arrow job.

The manifest is the SINGLE source of truth for what a job has durably
produced.  The commit protocol (docs/JOBS.md) is strictly ordered:

1. a shard's data/reject tables are written to ``*.tmp`` files,
   flushed, **fsync**\\ ed, then atomically **renamed** into place;
2. only then is the shard's :class:`ShardRecord` added to the manifest,
   which is itself rewritten atomically (tmp -> fsync -> rename, plus a
   directory fsync so the rename survives a power cut).

A shard therefore exists in exactly one of two states after ANY crash:
committed (recorded in the manifest, its files complete and hashed) or
not committed (absent from the manifest; any leftover ``*.tmp`` debris
or orphaned output file is overwritten deterministically on resume).
There is no third state — that is what makes ``resume()`` exactly-once:
committed shards are skipped wholesale, everything else replays from
the corpus, and replay is deterministic (same shard plan, same batch
splits, same parse), so the merged output is byte-identical to an
undisturbed run's.

The ``job`` fingerprint block pins everything that determines output
bytes (format, fields, sources, shard/batch geometry).  A resume
against a manifest whose fingerprint disagrees is REFUSED — silently
mixing two configurations' shards would corrupt the corpus without any
crash at all.

Pod jobs (docs/JOBS.md "Pod jobs") stack one level on top: each host of
an N-host pod commits its own shard subset into a PER-HOST manifest
(``manifest.host-NNN.json`` — same schema, same fingerprint block, same
atomic rewrite), and :func:`merge_manifests` folds every host's commit
log into the single top-level ``manifest.json`` — after which the pod
directory is indistinguishable from a single-host job's: ``merged_hash``
reads it, resume skips its shards, and a dead host's unfinished range is
just a run of uncommitted shards.  The merge REFUSES fingerprint
divergence across hosts (two configurations' shards must never mix) and
refuses conflicting duplicate commits (two hosts claiming one shard with
different content hashes); identical duplicates — a shard re-run by a
rebalanced host assignment — deduplicate cleanly because parse and
framing are deterministic.

Everything here is stdlib-only (json/os/hashlib/re) and jax-free.
"""
from __future__ import annotations

import json
import os
import re
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1
HOST_MANIFEST_FMT = "manifest.host-{index:03d}.json"
# 3+ digits: host_manifest_name's {index:03d} WIDENS past 999, and a
# pod of 1000+ hosts must not have its tail's commit logs silently
# invisible to merge/resume.
_HOST_MANIFEST_RE = re.compile(r"^manifest\.host-(\d{3,})\.json$")


def host_manifest_name(host_index: int) -> str:
    """The per-host commit-log filename of pod host ``host_index``."""
    return HOST_MANIFEST_FMT.format(index=int(host_index))


def count_committed_shards(out_dir: str, name: str = MANIFEST_NAME) -> int:
    """Committed-shard count per the on-disk commit log, tolerant of an
    absent/mid-rewrite file (atomic rename makes a torn read
    impossible; an unreadable log simply counts 0).  The one home of
    the poll the kill/preemption drills and the pod preemption watcher
    all run."""
    try:
        with open(os.path.join(out_dir, name), "rb") as f:
            return len(json.loads(f.read().decode()).get("shards", {}))
    except (OSError, ValueError):
        return 0


def list_host_manifests(out_dir: str) -> List[Tuple[int, str]]:
    """``(host_index, filename)`` for every per-host manifest present in
    ``out_dir``, sorted by host index."""
    try:
        names = os.listdir(out_dir)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        m = _HOST_MANIFEST_RE.match(n)
        if m:
            out.append((int(m.group(1)), n))
    return sorted(out)


class ManifestError(RuntimeError):
    """The manifest is unreadable, structurally invalid, or belongs to a
    different job configuration than the one asking to resume."""


@dataclass
class ShardRecord:
    """One committed shard: identity (the GLOBAL plan index + raw byte
    range), volume, output files and their content hashes."""

    shard: int                 # global shard index in the job plan
    source: int                # index into the job's source list
    start: int                 # raw byte range (pre-healing)
    end: int
    lines: int                 # lines parsed (valid + rejected)
    rows: int                  # data rows written (valid lines)
    rejects: int               # reject-table rows
    payload_bytes: int         # healed payload bytes parsed
    data_file: Optional[str]   # relative filename; None when rows == 0
    reject_file: Optional[str]  # relative filename; None when rejects == 0
    data_hash: Optional[str]   # blake2b hex of the data file bytes
    reject_hash: Optional[str]
    # Analytics pushdown (docs/ANALYTICS.md): aggregate-mode shards land
    # a partial-aggregate sidecar instead of a data table.  Defaulted so
    # pre-analytics manifests load unchanged (from_dict -> None).
    agg_file: Optional[str] = None
    agg_hash: Optional[str] = None
    committed_at: float = 0.0  # wall clock; NOT part of output identity

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ShardRecord":
        return cls(**{k: d.get(k) for k in cls.__dataclass_fields__})


def host_token() -> str:
    """This machine's identity as embedded in temp-file names
    (sanitized to the temp-name alphabet so parsing stays
    unambiguous)."""
    return re.sub(r"[^A-Za-z0-9_-]", "_", os.uname().nodename) or "host"


def temp_suffix() -> str:
    """The durable-write temp-file suffix: ``.<host>.<pid>.tmp`` —
    enough identity that a (re)starting pod host can tell in-flight
    writes from crash debris without any coordination: a LOCAL pid is
    checkable with ``os.kill(pid, 0)``, a FOREIGN host's temp is only
    debris once it has sat untouched for a long stale window
    (``jobs.writer.sweepable_temp_files``)."""
    return f".{host_token()}.{os.getpid()}.tmp"


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed entry survives a power cut
    (rename is atomic but not durable until the directory metadata is
    flushed).  Best-effort on filesystems that refuse O_RDONLY dir
    fsync (the rename is still atomic there)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp -> flush -> fsync -> rename -> dir fsync.  The reader either
    sees the whole previous version or the whole new one, never a
    torn write.  The temp name embeds the writer's host + pid so a
    concurrently (re)starting pod host's debris sweep (dead LOCAL pids
    only; foreign-host temps only after a long stale window) can never
    unlink an in-flight write — local or remote."""
    tmp = path + temp_suffix()
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(path) or ".")


@dataclass
class JobManifest:
    """The on-disk commit log (see module docstring)."""

    job: Dict[str, Any]                      # the config fingerprint block
    shards: Dict[int, ShardRecord] = field(default_factory=dict)
    version: int = MANIFEST_VERSION
    created_at: float = 0.0

    # -- construction / io ----------------------------------------------

    @classmethod
    def fresh(cls, fingerprint: Dict[str, Any]) -> "JobManifest":
        return cls(job=dict(fingerprint), created_at=time.time())

    @classmethod
    def load(cls, out_dir: str,
             name: str = MANIFEST_NAME) -> Optional["JobManifest"]:
        """The manifest of ``out_dir`` (by default the top-level one;
        ``name`` selects a per-host commit log), or None when none
        exists.  Raises :class:`ManifestError` on a corrupt/foreign
        file — a half-written manifest cannot exist under the
        atomic-write protocol, so corruption means outside interference
        and must not be silently treated as 'no job here'."""
        path = os.path.join(out_dir, name)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                raw = json.loads(f.read().decode("utf-8"))
            if raw.get("version") != MANIFEST_VERSION:
                raise ManifestError(
                    f"manifest version {raw.get('version')!r} != "
                    f"{MANIFEST_VERSION} (written by a different build?)"
                )
            shards = {
                int(k): ShardRecord.from_dict(v)
                for k, v in raw.get("shards", {}).items()
            }
            return cls(
                job=raw["job"], shards=shards,
                version=raw["version"],
                created_at=raw.get("created_at", 0.0),
            )
        except ManifestError:
            raise
        except Exception as e:  # noqa: BLE001 — corrupt json/schema
            raise ManifestError(
                f"unreadable manifest at {path}: {type(e).__name__}: {e}"
            ) from e

    def serialize(self) -> bytes:
        payload = {
            "version": self.version,
            "created_at": self.created_at,
            "job": self.job,
            "shards": {
                str(k): asdict(v) for k, v in sorted(self.shards.items())
            },
        }
        return json.dumps(payload, indent=1, sort_keys=True).encode("utf-8")

    def save(self, out_dir: str, name: str = MANIFEST_NAME) -> None:
        atomic_write_bytes(
            os.path.join(out_dir, name), self.serialize()
        )

    # -- commit log -----------------------------------------------------

    def commit(self, out_dir: str, record: ShardRecord,
               write_bytes=None, name: str = MANIFEST_NAME) -> None:
        """Record one shard as durably written — THE single commit
        path.  The caller has already renamed the shard's files into
        place; once the manifest rewrite lands, resume skips the shard
        forever.  ``write_bytes(name, data)`` overrides the write (the
        job runner routes it through its retrying
        :class:`~logparser_tpu.jobs.writer.JobWriter`); ``name`` selects
        the on-disk commit log (a pod host commits into ITS host
        manifest, never the shared top-level one — the merge step owns
        that).  On ANY write failure the record is rolled back out of
        the in-memory map so the manifest object still mirrors the disk
        truth."""
        record.committed_at = time.time()
        self.shards[record.shard] = record
        try:
            if write_bytes is not None:
                write_bytes(name, self.serialize())
            else:
                self.save(out_dir, name)
        except BaseException:
            del self.shards[record.shard]
            raise

    def committed_indices(self) -> List[int]:
        return sorted(self.shards)

    # -- fingerprinting -------------------------------------------------

    def mismatch(self, fingerprint: Dict[str, Any]) -> Optional[str]:
        """None when ``fingerprint`` matches this manifest's job block;
        otherwise a human-readable description of the first divergence
        (the resume refusal message)."""
        for key in sorted(set(self.job) | set(fingerprint)):
            a, b = self.job.get(key), fingerprint.get(key)
            if a != b:
                return f"{key}: manifest has {a!r}, job has {b!r}"
        return None


# ---------------------------------------------------------------------------
# pod-level manifest MERGE
# ---------------------------------------------------------------------------


def _records_equal(a: ShardRecord, b: ShardRecord) -> bool:
    """Output identity of two commit records: everything except the
    commit wall-clock (deterministic replay of one shard by two hosts
    produces identical records apart from ``committed_at``)."""
    da, db = asdict(a), asdict(b)
    da.pop("committed_at", None)
    db.pop("committed_at", None)
    return da == db


def _fold_shards(out_dir: str,
                 sources: List[Tuple[str, JobManifest]]
                 ) -> Dict[int, ShardRecord]:
    """THE one duplicate-commit policy, shared by merge and resume: fold
    every source's shard records into one map — identical duplicate
    records dedupe (deterministic replay under a changed host
    assignment), a conflicting pair is a :class:`ManifestError` (the
    on-disk shard files can match at most one of them)."""
    out: Dict[int, ShardRecord] = {}
    owner: Dict[int, str] = {}
    for name, m in sources:
        for idx, rec in m.shards.items():
            prev = out.get(idx)
            if prev is None:
                out[idx] = rec
                owner[idx] = name
            elif not _records_equal(prev, rec):
                raise ManifestError(
                    f"refusing {out_dir}: shard {idx} committed by "
                    f"both {owner[idx]} and {name} with DIVERGING "
                    "records — the on-disk shard files can match at "
                    "most one of them"
                )
    return out


def merge_manifests(out_dir: str, write_bytes=None) -> JobManifest:
    """Fold every per-host commit log (plus any existing top-level
    manifest) of a pod job directory into ONE merged ``manifest.json``
    — the step that makes a pod job resume exactly like a single-host
    one (docs/JOBS.md "Pod jobs").

    Safety rules (each a :class:`ManifestError`):

    - every manifest's ``job`` fingerprint block must be identical —
      shards of two configurations must never mix (the cross-host twin
      of the single-host resume refusal);
    - a shard committed by MORE than one manifest must carry identical
      records (content hashes included).  Identical duplicates dedupe
      (deterministic replay under a changed host assignment); a
      conflicting pair is refused loudly — one of the two output files
      was overwritten and the survivor can only match one record.

    Partial merges are the NORMAL case mid-pod (a dead host's range is
    simply absent) and the merge is idempotent: re-running it over the
    same directory, with or without new host commits, converges.  The
    merged manifest is written atomically via ``write_bytes(name,
    data)`` when given (the pod runner routes it through a retrying
    writer), else :func:`atomic_write_bytes`.  Host manifests are left
    in place — they are each host's durable truth and re-merging is
    free."""
    sources: List[Tuple[str, JobManifest]] = []
    top = JobManifest.load(out_dir)
    if top is not None:
        sources.append((MANIFEST_NAME, top))
    for _, name in list_host_manifests(out_dir):
        m = JobManifest.load(out_dir, name)
        if m is not None:
            sources.append((name, m))
    if not sources:
        raise ManifestError(f"{out_dir}: no manifest to merge")
    ref_name, ref = sources[0]
    for name, m in sources[1:]:
        diff = ref.mismatch(m.job)
        if diff:
            raise ManifestError(
                f"refusing to merge {out_dir}: {name} belongs to a "
                f"different job than {ref_name} ({diff})"
            )
    merged = JobManifest(
        job=dict(ref.job),
        created_at=min(m.created_at for _, m in sources
                       if m.created_at) if any(
            m.created_at for _, m in sources) else ref.created_at,
        shards=_fold_shards(out_dir, sources),
    )
    data = merged.serialize()
    if write_bytes is not None:
        write_bytes(MANIFEST_NAME, data)
    else:
        atomic_write_bytes(os.path.join(out_dir, MANIFEST_NAME), data)
    return merged


def committed_anywhere(out_dir: str,
                       fingerprint: Optional[Dict[str, Any]] = None,
                       preloaded: Optional[Dict[str, JobManifest]] = None
                       ) -> Dict[int, ShardRecord]:
    """The union of committed shard records across the top-level
    manifest AND every per-host manifest — what a (re)starting host must
    skip, whether or not a merge has run yet.  With ``fingerprint``,
    every manifest found is checked against it first (a foreign commit
    log in the directory is refused, mirroring the resume refusal).
    ``preloaded`` (name -> manifest) supplies commit logs the caller
    already holds, which are folded without a redundant disk read —
    resume of a many-thousand-shard job must not parse its own O(shards)
    JSON twice."""
    preloaded = preloaded or {}
    sources: List[Tuple[str, JobManifest]] = []
    names = [MANIFEST_NAME] + [n for _, n in list_host_manifests(out_dir)]
    for name in names:
        m = preloaded.get(name) or JobManifest.load(out_dir, name)
        if m is None:
            continue
        if fingerprint is not None:
            diff = m.mismatch(fingerprint)
            if diff:
                raise ManifestError(
                    f"refusing to resume {out_dir}: {name} belongs to "
                    f"a different job ({diff})"
                )
        sources.append((name, m))
    return _fold_shards(out_dir, sources)
