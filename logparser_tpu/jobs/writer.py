"""Shard output writer: Arrow tables -> durable files, atomically.

One shard produces up to two Arrow IPC stream files in the job
directory:

- ``shard-NNNNN.arrow`` — the data table (valid lines only, the
  parser's copy-mode Arrow schema), omitted when the shard has no
  valid line;
- ``shard-NNNNN.rejects.arrow`` — the reject table (one row per line
  that failed BOTH device parse and oracle rescue: shard, batch, line
  offset, stable reason, raw line bytes), omitted when clean.

Every file lands via temp-file -> flush -> fsync -> atomic rename (the
manifest commit happens AFTER, in the runner) so a crash at any byte
leaves either no file or a complete one — never a torn table.

Writer I/O faults (real ENOSPC/EIO, or injected through the chaos
grammar's ``io_error``/``enospc`` primitives) retry with bounded
exponential backoff; a shard that exhausts its retries raises
:class:`ShardWriteError`, which the runner records as a FAILED shard —
the job continues, the manifest stays consistent (no entry), and a
later resume retries the shard from the corpus.
"""
from __future__ import annotations

import hashlib
import logging
import os
import re
import time
from typing import Any, List, Optional, Tuple

from ..observability import log_warning_once, metrics, observe_stage
from .manifest import (
    JobManifest,
    ShardRecord,
    fsync_dir,
    host_token,
    temp_suffix,
)

LOG = logging.getLogger(__name__)

DATA_FILE = "shard-{index:05d}.arrow"
REJECT_FILE = "shard-{index:05d}.rejects.arrow"
# Aggregate-mode sidecar (docs/ANALYTICS.md): one partial-aggregate
# frame per shard, committed through the same temp->fsync->rename->
# manifest protocol — always written (even for an empty shard) so a
# committed aggregate shard's record always carries its sidecar.
AGG_FILE = "shard-{index:05d}.agg.arrow"

#: The writer's retryable operations (chaos injection points share the
#: names: ``io_error:op=write`` etc.).
WRITE_OPS = ("write", "fsync", "rename")


class ShardWriteError(RuntimeError):
    """One shard's output could not be durably written even after the
    bounded retry ladder.  Carries the shard index; the job survives."""

    def __init__(self, shard: int, message: str):
        super().__init__(message)
        self.shard = shard


def reject_schema():
    import pyarrow as pa

    return pa.schema([
        ("shard", pa.int64()),       # global shard index
        ("batch", pa.int32()),       # batch index within the shard
        ("line", pa.int64()),        # line offset within the shard
        ("reason", pa.string()),     # stable vocabulary (BatchResult)
        ("raw", pa.binary()),        # the line bytes, verbatim
    ])


def build_reject_table(rows: List[Tuple[int, int, int, str, bytes]]):
    """rows = [(shard, batch, line, reason, raw_bytes), ...] in line
    order -> the reject table (schema above)."""
    import pyarrow as pa

    schema = reject_schema()
    if not rows:
        return pa.table(
            {f.name: pa.array([], type=f.type) for f in schema}
        )
    cols = list(zip(*rows))
    return pa.table({
        "shard": pa.array(cols[0], type=pa.int64()),
        "batch": pa.array(cols[1], type=pa.int32()),
        "line": pa.array(cols[2], type=pa.int64()),
        "reason": pa.array(cols[3], type=pa.string()),
        "raw": pa.array(cols[4], type=pa.binary()),
    })


class JobWriter:
    """Durable shard writer for one job directory.  ``retries`` bounds
    the per-operation retry ladder (attempts = retries + 1), backoff
    doubling from ``backoff_base_s``; ``chaos`` is a
    :class:`~logparser_tpu.tools.chaos.WriterChaos` (or None)."""

    def __init__(self, out_dir: str, retries: int = 3,
                 backoff_base_s: float = 0.05, chaos: Any = None):
        self.out_dir = out_dir
        self.retries = max(0, int(retries))
        self.backoff_base_s = float(backoff_base_s)
        self.chaos = chaos

    # -- low-level: one durable file ------------------------------------

    def _attempt(self, path: str, data: bytes, shard: int) -> None:
        """One write->fsync->rename pass with chaos injection at each
        op.  Any OSError propagates to the retry ladder."""
        chaos = self.chaos
        tmp = path + temp_suffix()
        try:
            if chaos:
                chaos.check("write", shard)
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                if chaos:
                    chaos.check("fsync", shard)
                os.fsync(f.fileno())
            if chaos:
                chaos.check("rename", shard)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        fsync_dir(self.out_dir)

    def write_file(self, name: str, data: bytes, shard: int) -> None:
        """Durably land ``data`` at ``out_dir/name``, retrying transient
        I/O faults with bounded backoff.  Raises ShardWriteError once
        the ladder is exhausted — the caller fails the SHARD, never the
        job."""
        path = os.path.join(self.out_dir, name)
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            if attempt:
                delay = self.backoff_base_s * (2 ** (attempt - 1))
                time.sleep(delay)
            try:
                self._attempt(path, data, shard)
                return
            except OSError as e:
                last = e
                metrics().increment(
                    "job_writer_retries_total",
                    labels={"op": _op_of(e)},
                )
                # Static warn-once key (per-file/per-error text would
                # grow the warn-once table shard by shard on a big
                # job); specifics ride the counter labels and DEBUG.
                log_warning_once(
                    LOG,
                    "job writer: transient I/O fault(s); retrying with "
                    "bounded backoff (job_writer_retries_total counts "
                    "them; details at DEBUG)",
                )
                LOG.debug("job writer: %s attempt %d failed (%s: %s)",
                          name, attempt + 1, type(e).__name__, e)
        raise ShardWriteError(
            shard,
            f"shard {shard}: {name} failed after "
            f"{self.retries + 1} attempts ({last})",
        )

    # -- shard commit ---------------------------------------------------

    def write_shard(self, shard, data_table, reject_rows, lines: int,
                    payload_bytes: int, agg_state: Any = None,
                    agg_rows: int = 0) -> ShardRecord:
        """Land one shard's outputs and return its (uncommitted)
        :class:`ShardRecord` — the runner appends it to the manifest,
        which is the actual commit point.  ``agg_state`` (aggregate-mode
        jobs) lands the shard's partial-aggregate sidecar instead of a
        data table; ``agg_rows`` records the shard's good-line count in
        the record's ``rows`` field (there is no data table to count)."""
        from ..tpu.arrow_bridge import table_to_ipc_bytes

        t0 = time.perf_counter()
        reg = metrics()
        data_file = data_hash = None
        reject_file = reject_hash = None
        agg_file = agg_hash = None
        rows = 0
        if data_table is not None and data_table.num_rows:
            rows = int(data_table.num_rows)
            data = table_to_ipc_bytes(data_table.combine_chunks())
            data_file = DATA_FILE.format(index=shard.index)
            data_hash = hashlib.blake2b(data).hexdigest()
            self.write_file(data_file, data, shard.index)
            reg.increment("job_bytes_written_total", len(data))
        if agg_state is not None:
            rows = int(agg_rows)
            data = agg_state.to_ipc_bytes()
            agg_file = AGG_FILE.format(index=shard.index)
            agg_hash = hashlib.blake2b(data).hexdigest()
            self.write_file(agg_file, data, shard.index)
            reg.increment("job_bytes_written_total", len(data))
        if reject_rows:
            reject = table_to_ipc_bytes(build_reject_table(reject_rows))
            reject_file = REJECT_FILE.format(index=shard.index)
            reject_hash = hashlib.blake2b(reject).hexdigest()
            self.write_file(reject_file, reject, shard.index)
            reg.increment("job_bytes_written_total", len(reject))
        observe_stage("job_write", time.perf_counter() - t0, items=rows)
        reg.increment("job_rows_total", rows)
        return ShardRecord(
            shard=shard.index, source=shard.source,
            start=shard.start, end=shard.end,
            lines=lines, rows=rows, rejects=len(reject_rows),
            payload_bytes=payload_bytes,
            data_file=data_file, reject_file=reject_file,
            data_hash=data_hash, reject_hash=reject_hash,
            agg_file=agg_file, agg_hash=agg_hash,
        )


def _op_of(e: OSError) -> str:
    import errno

    if getattr(e, "errno", None) == errno.ENOSPC:
        return "enospc"
    return "io_error"


def merged_hash(out_dir: str, manifest: JobManifest) -> str:
    """Content hash of the job's durable output: every committed
    shard's data bytes then reject bytes, in global shard order — the
    byte-identity probe the kill-drill invariant is asserted with
    (docs/JOBS.md)."""
    h = hashlib.blake2b()
    for idx in manifest.committed_indices():
        rec = manifest.shards[idx]
        for name in (rec.data_file, rec.reject_file):
            if name is None:
                h.update(b"\0")
                continue
            with open(os.path.join(out_dir, name), "rb") as f:
                h.update(f.read())
        # Aggregate sidecars join the identity only when present, so a
        # pre-analytics job's hash is unchanged byte for byte.
        if rec.agg_file is not None:
            with open(os.path.join(out_dir, rec.agg_file), "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def merged_job_aggregate(out_dir: str,
                         manifest: Optional[JobManifest] = None):
    """Merge every committed shard's partial-aggregate sidecar — in
    global shard order — into one
    :class:`~logparser_tpu.analytics.state.AggregateState`: the job-level
    aggregate answer (docs/ANALYTICS.md).  Order is cosmetic (the merge
    is associative and commutative) but fixed, so two resumed/pod runs
    of one job produce byte-identical merged frames."""
    from ..analytics.spec import AggregateSpec
    from ..analytics.state import AggregateState

    if manifest is None:
        manifest = JobManifest.load(out_dir)
        if manifest is None:
            raise ValueError(f"{out_dir}: no manifest to aggregate")
    key = manifest.job.get("aggregate")
    if not key:
        raise ValueError(f"{out_dir}: not an aggregate-mode job")
    spec = AggregateSpec.from_canonical(key)
    total = AggregateState(spec)
    for idx in manifest.committed_indices():
        rec = manifest.shards[idx]
        if rec.agg_file is None:
            continue
        with open(os.path.join(out_dir, rec.agg_file), "rb") as f:
            total.merge(AggregateState.from_ipc_bytes(f.read(), spec))
    return total


def leaked_temp_files(out_dir: str) -> List[str]:
    """``*.tmp`` debris in the job directory (crash leftovers; resume
    sweeps them, the smoke asserts none survive a completed run)."""
    try:
        return sorted(
            n for n in os.listdir(out_dir) if n.endswith(".tmp")
        )
    except FileNotFoundError:
        return []


_TMP_RE = re.compile(r"\.(?:([A-Za-z0-9_-]+)\.)?(\d+)\.tmp$")

#: A FOREIGN host's temp file (pod over a shared filesystem: its pid is
#: meaningless here) is only swept once it has sat untouched this long —
#: in-flight writes live milliseconds to seconds, so anything this old
#: is crash debris from a machine that went away.
FOREIGN_TMP_STALE_S = 900.0


def sweepable_temp_files(out_dir: str) -> List[str]:
    """The subset of :func:`leaked_temp_files` a (re)starting run may
    safely unlink.  In a POD directory a temp file can belong to
    another host's IN-FLIGHT write, so the rules are:

    - SAME machine (or a legacy name with no host token): sweep only
      when the embedded pid is dead — a live pid is a concurrent local
      host mid-write;
    - FOREIGN machine (shared-filesystem pod: the pid is meaningless
      here): sweep only when the file has sat untouched past
      :data:`FOREIGN_TMP_STALE_S` — a remote host's in-flight write is
      always fresh;
    - no parseable identity at all: legacy debris, sweepable."""
    local = host_token()
    out = []
    now = time.time()
    for name in leaked_temp_files(out_dir):
        m = _TMP_RE.search(name)
        if m:
            host, pid = m.group(1), int(m.group(2))
            if host is None or host == local:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    pass        # dead local writer: crash debris
                except OSError:
                    continue    # unknowable: leave it alone
                else:
                    continue    # alive: a concurrent local host, or us
            else:
                try:
                    age = now - os.stat(
                        os.path.join(out_dir, name)).st_mtime
                except OSError:
                    continue    # vanished mid-scan: its owner is live
                if age < FOREIGN_TMP_STALE_S:
                    continue    # a remote host may be mid-write
        out.append(name)
    return out
