"""Durable corpus -> sharded-Arrow job runner (docs/JOBS.md).

``run_job(JobSpec(...))`` drives the whole batch tier: the feeder
fabric's shard planner tiles the corpus (``feeder/shards.py`` — the
reference's InputFormat split semantics), a supervised
:class:`~logparser_tpu.feeder.pool.FeederPool` reads + frames shards in
parallel, ``TpuBatchParser.parse_batch_stream`` parses them on device
with host-stage overlap, and every shard's results land as Arrow IPC
files through the atomic :class:`~logparser_tpu.jobs.writer.JobWriter`,
committed one at a time into the JSON manifest
(:mod:`~logparser_tpu.jobs.manifest`).

Durability contract (the kill-drill invariant, gated in ``bench.py``
and drilled by ``make job-smoke``):

- a shard is COMMITTED exactly when its manifest entry exists; its
  files were renamed into place (and fsynced) strictly before;
- ``run_job(..., resume=True)`` over an interrupted directory skips
  committed shards wholesale (they are never re-parsed) and replays
  only the rest from the corpus — parse and framing are deterministic,
  so the merged output (data + reject tables, global shard order) is
  BYTE-IDENTICAL to an undisturbed run's;
- a line that fails both device parse and oracle rescue is never
  dropped silently and never raises: it lands in the shard's reject
  table with a stable reason (``BatchResult.reject_reasons``) and
  counts ``job_rejected_lines_total{reason}``;
- writer I/O faults retry with bounded backoff, then fail the SHARD
  (recorded on the report, absent from the manifest — a later resume
  retries it); the job itself completes every other shard.
"""
from __future__ import annotations

import hashlib
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..feeder.pool import FeederPool, default_feeder_workers
from ..feeder.shards import (
    DEFAULT_SHARD_BYTES,
    Shard,
    SourceT,
    normalize_sources,
    plan_shards,
    shards_for_host,
)
from ..observability import log_warning_once, metrics
from .manifest import (
    MANIFEST_NAME,
    JobManifest,
    ManifestError,
    committed_anywhere,
    host_manifest_name,
)
from .writer import JobWriter, ShardWriteError, sweepable_temp_files

LOG = logging.getLogger(__name__)

DEFAULT_JOB_BATCH_LINES = 16384

#: CLI exit code for a SIGTERM-clean (preempted) run: the current shard
#: boundary was committed and the manifest resumes exactly — the
#: cloud-TPU preemption notice's cheap exit (docs/JOBS.md "Preemption").
#: Distinct from 1 (failed shards) and 2 (config error): an orchestrator
#: relaunches a 3 unconditionally, resume re-parses zero committed
#: shards.
EXIT_PREEMPTED = 3


@dataclass
class JobSpec:
    """Everything that determines a job's output bytes, plus execution
    knobs that don't (worker count, transport) — only the former enter
    the manifest fingerprint."""

    sources: Sequence[SourceT]
    log_format: str
    fields: Sequence[str]
    out_dir: str
    shard_bytes: int = DEFAULT_SHARD_BYTES
    batch_lines: int = DEFAULT_JOB_BATCH_LINES
    # Execution-only knobs (not fingerprinted):
    workers: Optional[int] = None
    use_processes: Optional[bool] = None
    transport: Optional[str] = None
    # Pod placement (docs/JOBS.md "Pod jobs"): this run owns host
    # ``host_index``'s contiguous slice of the GLOBAL shard plan and
    # commits into its per-host manifest.  Execution-only — the shard
    # plan, and therefore the merged output bytes, are identical for
    # every n_hosts, which is exactly what makes an N-host pod's merged
    # output byte-comparable to a single-host run's.
    n_hosts: int = 1
    host_index: int = 0
    # Device-side data parallelism: lay the parse step over this many
    # local devices (``TpuBatchParser(data_parallel=...)``); None = the
    # parser default (single device).
    data_parallel: Optional[int] = None
    # Analytics pushdown (docs/ANALYTICS.md): an aggregation spec (op
    # list / JSON string / AggregateSpec) switches the job to aggregate
    # mode — each shard lands a partial-aggregate sidecar instead of a
    # data table (rejects still land).  FINGERPRINTED: the spec
    # determines the output bytes, so resuming a row job as an
    # aggregate job (or across two specs) is refused.
    aggregate: Optional[Any] = None

    def fingerprint(self, sources_norm) -> Dict[str, Any]:
        """The manifest's job block: resume refuses when any of this
        diverges (mixing configurations would corrupt the corpus)."""
        descr = []
        for s in sources_norm:
            if s.kind == "file":
                # path + size + mtime: a corpus rewritten IN PLACE to
                # the same byte size (rotate-and-refill) must refuse to
                # resume — mixing two corpora's shards would corrupt
                # the output with no crash at all.
                try:
                    mtime_ns = os.stat(s.path).st_mtime_ns
                except OSError:
                    mtime_ns = None
                descr.append({
                    "kind": "file",
                    "path": os.path.abspath(s.path),
                    "size": s.size,
                    "mtime_ns": mtime_ns,
                })
            else:
                descr.append({
                    "kind": "blob",
                    "size": s.size,
                    "hash": hashlib.blake2b(s.blob).hexdigest()[:32],
                })
        from ..analytics.spec import parse_aggregate_config, spec_tuple

        return {
            "log_format": self.log_format,
            "fields": list(self.fields),
            "shard_bytes": int(self.shard_bytes),
            "batch_lines": int(self.batch_lines),
            "sources": descr,
            # None for row jobs: a pre-analytics manifest's absent key
            # reads back as None too, so old row jobs still resume.
            "aggregate": spec_tuple(
                parse_aggregate_config(self.aggregate)
            ),
        }


@dataclass
class JobPolicy:
    """Runner tunables (all have safe defaults)."""

    io_retries: int = 3          # writer attempts = io_retries + 1
    io_backoff_s: float = 0.05   # backoff base, doubling per retry
    # Crash simulation for tests/bench: abandon the run (WITHOUT
    # committing anything further) after this many shard commits this
    # run — models a kill landing on a commit boundary; the real
    # SIGKILL drill lives in tools/job_smoke.py.
    stop_after_shards: Optional[int] = None
    # Graceful preemption: an Event-like object (``is_set() -> bool``)
    # checked at every shard commit boundary — when set, the run
    # commits the shard in flight, marks the report ``preempted``, and
    # returns (the CLI installs its SIGTERM handler here and exits
    # EXIT_PREEMPTED; docs/JOBS.md "Preemption").  Cheaper than the
    # SIGKILL path by exactly one replayed shard.
    stop_event: Optional[Any] = None


@dataclass
class JobReport:
    """What one ``run_job`` call did (this run only; the manifest holds
    the cumulative truth)."""

    out_dir: str
    shards_total: int = 0
    committed: int = 0           # committed by THIS run
    skipped: int = 0             # committed before this run (resume)
    failed: List[Dict[str, Any]] = field(default_factory=list)
    lines: int = 0
    rows: int = 0
    rejects: int = 0
    reject_reasons: Dict[str, int] = field(default_factory=dict)
    payload_bytes: int = 0
    wall_s: float = 0.0
    stopped_early: bool = False  # JobPolicy.stop_after_shards tripped
    preempted: bool = False      # JobPolicy.stop_event fired (SIGTERM)
    n_hosts: int = 1             # pod placement (1 = single-host job)
    host_index: int = 0

    @property
    def complete(self) -> bool:
        return (not self.failed and not self.stopped_early
                and self.committed + self.skipped == self.shards_total)

    @property
    def bytes_per_sec(self) -> float:
        return self.payload_bytes / self.wall_s if self.wall_s > 0 else 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "out_dir": self.out_dir,
            "shards_total": self.shards_total,
            "committed": self.committed,
            "skipped": self.skipped,
            "failed": self.failed,
            "lines": self.lines,
            "rows": self.rows,
            "rejects": self.rejects,
            "reject_reasons": self.reject_reasons,
            "payload_bytes": self.payload_bytes,
            "wall_s": round(self.wall_s, 4),
            "bytes_per_sec": round(self.bytes_per_sec, 1),
            "complete": self.complete,
            "stopped_early": self.stopped_early,
            **({"preempted": True} if self.preempted else {}),
            **({"n_hosts": self.n_hosts, "host_index": self.host_index}
               if self.n_hosts > 1 else {}),
        }


class _ShardAccumulator:
    """Per-shard in-flight state: filtered data tables, reject rows,
    and volume counters, until the shard's last batch lands.  Reject
    REASON tallies stay here until the shard actually commits — report
    totals and ``job_rejected_lines_total`` must equal lines durably
    landed in reject tables (a failed or replayed shard's rejects are
    not double-counted)."""

    __slots__ = ("tables", "rejects", "reason_counts", "lines",
                 "payload_bytes", "agg", "rows")

    def __init__(self) -> None:
        self.tables: List[Any] = []
        self.rejects: List[tuple] = []
        self.reason_counts: Dict[str, int] = {}
        self.lines = 0
        self.payload_bytes = 0
        # Aggregate mode: the shard's merged partial state + good-line
        # count (there is no data table to count rows from).
        self.agg: Any = None
        self.rows = 0


def _split_chaos(chaos: Any):
    """(pool ChaosSpec or None, WriterChaos or None, DeviceChaos or
    None) from whatever the caller armed: a spec object, the string
    grammar, or the env var.  Worker faults go to the feeder fabric, io
    faults to the writer, device faults to the parser's fault layer;
    pod faults (``preempt_host``) are the pod runner's and inert here."""
    from ..tools.chaos import (
        DEVICE_FAULTS,
        IO_FAULTS,
        POD_FAULTS,
        ChaosSpec,
        DeviceChaos,
        WriterChaos,
    )

    if chaos is None:
        spec = ChaosSpec.from_env()
    elif isinstance(chaos, str):
        spec = ChaosSpec.parse(chaos)
    else:
        spec = chaos
    if spec is None:
        return None, None, None
    pool_faults = [
        f for f in spec.faults
        if f.kind not in IO_FAULTS | DEVICE_FAULTS | POD_FAULTS
    ]
    writer = WriterChaos(spec)
    device = DeviceChaos(spec)
    return (
        ChaosSpec(pool_faults) if pool_faults else None,
        writer if writer else None,
        device if device else None,
    )


def run_job(
    spec: JobSpec,
    resume: bool = True,
    parser: Any = None,
    chaos: Any = None,
    policy: Optional[JobPolicy] = None,
) -> JobReport:
    """Run (or resume) one durable job.  See module docstring.

    ``parser`` lets a caller reuse a compiled ``TpuBatchParser`` (its
    config must match the spec — bench/smoke reuse the session parser
    to keep jit compiles out of timed windows).  ``chaos`` arms fault
    injection (``ChaosSpec`` / grammar string; default: the
    ``LOGPARSER_TPU_CHAOS`` env var)."""
    policy = policy or JobPolicy()
    t_start = time.perf_counter()
    reg = metrics()
    if spec.n_hosts < 1 or not 0 <= spec.host_index < spec.n_hosts:
        raise ValueError(
            f"bad pod placement: host {spec.host_index} of "
            f"{spec.n_hosts}"
        )
    pod = spec.n_hosts > 1
    own_name = (host_manifest_name(spec.host_index) if pod
                else MANIFEST_NAME)
    from ..analytics.spec import parse_aggregate_config

    agg_spec = parse_aggregate_config(spec.aggregate)
    sources_norm = normalize_sources(spec.sources)
    plan = plan_shards(sources_norm, spec.shard_bytes)
    out_dir = spec.out_dir
    os.makedirs(out_dir, exist_ok=True)
    fingerprint = spec.fingerprint(sources_norm)
    manifest = JobManifest.load(out_dir, own_name)
    if manifest is not None:
        if not resume:
            raise ManifestError(
                f"{out_dir} already holds a job manifest; resume it "
                "(the default) or clear the directory for a fresh run"
            )
        mismatch = manifest.mismatch(fingerprint)
        if mismatch:
            raise ManifestError(
                f"refusing to resume {out_dir}: manifest belongs to a "
                f"different job ({mismatch})"
            )
    else:
        manifest = JobManifest.fresh(fingerprint)
        manifest.save(out_dir, own_name)
    # Crash debris: tmp files can only be leftovers of an interrupted,
    # uncommitted write — safe to sweep (committed files were renamed).
    # Pod-safe: only debris whose writer pid is dead is swept — another
    # live host's mid-write temp file must not be yanked out from under
    # its fsync (sweepable_temp_files applies the dead-pid rule).
    for name in sweepable_temp_files(out_dir):
        try:
            os.unlink(os.path.join(out_dir, name))
            reg.increment("job_temp_files_swept_total")
        except OSError as e:
            log_warning_once(LOG, f"job: could not sweep {name}: {e}")

    # What to skip: every shard durably committed by ANYONE — this
    # host's earlier runs, the merged top-level manifest, and (pod) the
    # other hosts' manifests.  A fingerprint divergence in any commit
    # log refuses the run, exactly like the single-manifest resume.
    committed_before = set(committed_anywhere(
        out_dir, fingerprint, preloaded={own_name: manifest}))
    owned = (shards_for_host(plan, spec.n_hosts, spec.host_index)
             if pod else plan)
    remaining = [s for s in owned if s.index not in committed_before]
    report = JobReport(out_dir=out_dir, shards_total=len(owned),
                       skipped=len(owned) - len(remaining),
                       n_hosts=spec.n_hosts, host_index=spec.host_index)
    if report.skipped:
        reg.increment("job_shards_skipped_total", report.skipped)
    pool_chaos, writer_chaos, device_chaos = _split_chaos(chaos)
    writer = JobWriter(out_dir, retries=policy.io_retries,
                       backoff_base_s=policy.io_backoff_s,
                       chaos=writer_chaos)
    reg.increment("job_runs_total")
    # One durable job = one connected trace (docs/OBSERVABILITY.md
    # "Tracing"): a pod launcher hands its context down via the
    # LOGPARSER_TPU_TRACEPARENT env; a standalone job head-samples
    # under LOGPARSER_TPU_TRACE_SAMPLE.  Feeder shards and shard
    # commits become child spans below.
    from ..tracing import child_span, root_span

    job_span = root_span(
        "job_run",
        traceparent=os.environ.get("LOGPARSER_TPU_TRACEPARENT"),
        attrs={"host_index": spec.host_index, "n_hosts": spec.n_hosts,
               "shards": len(owned)},
    )
    job_ctx = job_span.context if job_span is not None else None
    if not remaining:
        report.wall_s = time.perf_counter() - t_start
        if job_span is not None:
            job_span.end(committed=0, skipped=report.skipped)
        return report

    own_parser = parser is None
    # A caller-supplied parser joins the drill too (device faults belong
    # to the parse step wherever the parser came from) but is handed
    # back with its PRIOR arming restored in the finally below — a
    # caller mid-drill of its own must not find its injections wiped.
    armed_caller_parser = (not own_parser) and device_chaos is not None
    prior_device_chaos = (
        getattr(parser, "_device_chaos", None) if armed_caller_parser
        else None
    )
    if own_parser:
        from ..tpu.batch import TpuBatchParser

        # Jobs deliver copy-mode IPC tables, never string_view columns:
        # device view emission would be pure kernel + D2H waste here.
        # data_parallel lays the fused parse over this host's local
        # chips (jax.sharding mesh; docs/JOBS.md "Pod jobs").
        parser = TpuBatchParser(
            spec.log_format, list(spec.fields), view_fields=(),
            data_parallel=spec.data_parallel,
            device_chaos=device_chaos,
        )
        if chaos is not None and device_chaos is None:
            # An EXPLICIT chaos arg with no device faults must override
            # the constructor's env fallback — the caller already chose
            # this run's whole fault plan.
            parser.arm_device_chaos(None)
    elif armed_caller_parser:
        parser.arm_device_chaos(device_chaos)
    if agg_spec is not None:
        # Field-level spec validation needs the built parser; a bad
        # spec must refuse the job BEFORE the pool spins up (and must
        # not leak a just-built parser's worker pools).
        try:
            agg_spec.validate_for(parser)
        except Exception:
            if own_parser:
                parser.close()
            raise

    # The pool runs over a RENUMBERED plan (FeederPool requires index ==
    # position); remaining[pool_index] maps back to the global shard.
    pool_shards = [replace(s, index=i) for i, s in enumerate(remaining)]
    pool = FeederPool(
        spec.sources,
        workers=spec.workers or min(default_feeder_workers(),
                                    max(1, len(pool_shards))),
        shard_bytes=spec.shard_bytes,
        batch_lines=spec.batch_lines,
        transport=spec.transport,
        use_processes=spec.use_processes,
        chaos=pool_chaos,
        # A batch job's full queue is its healthy steady state, not
        # service overload — stay out of the admission signal.
        backpressure_signal=False,
        shard_plan=pool_shards,
    )

    meta: deque = deque()
    # One feeder_shard span per shard the fabric feeds: opened when the
    # shard's first batch arrives, closed when the next shard starts
    # (trailing span closed in the finally below).
    feed_state: Dict[str, Any] = {"shard": None, "span": None}

    def _tap(batches):
        for eb in batches:
            if job_ctx is not None and eb.shard != feed_state["shard"]:
                if feed_state["span"] is not None:
                    feed_state["span"].end()
                feed_state["shard"] = eb.shard
                feed_state["span"] = child_span(
                    "feeder_shard", job_ctx,
                    attrs={"shard": remaining[eb.shard].index},
                )
            meta.append((eb.shard, eb.index, eb.n_lines, eb.source_bytes))
            yield eb

    def _commit(pool_idx: int, acc: _ShardAccumulator) -> None:
        import pyarrow as pa

        shard = remaining[pool_idx]
        c_span = child_span("job_shard_commit", job_ctx,
                            attrs={"shard": shard.index})
        data_table = (
            pa.concat_tables(acc.tables) if acc.tables else None
        )
        def fail(e: ShardWriteError) -> None:
            report.failed.append({"shard": shard.index, "error": str(e)})
            reg.increment("job_shards_failed_total",
                          labels={"reason": "write_io"})
            if c_span is not None:
                c_span.end(outcome="failed")
            LOG.error("job: shard %d failed durably: %s", shard.index, e)

        agg_state = None
        if agg_spec is not None:
            # Always a sidecar, even for an empty shard: a committed
            # aggregate shard's record must carry its partial frame
            # (merged_job_aggregate folds records, not directory scans).
            from ..analytics.state import AggregateState

            agg_state = (acc.agg if acc.agg is not None
                         else AggregateState(agg_spec))
        try:
            record = writer.write_shard(
                shard, data_table, acc.rejects, acc.lines,
                acc.payload_bytes, agg_state=agg_state, agg_rows=acc.rows,
            )
        except ShardWriteError as e:
            fail(e)
            return
        # The manifest rewrite is the commit point, and it writes to the
        # same disk the shard files just hit — route it through the same
        # bounded retry ladder, and on exhaustion fail the SHARD (its
        # renamed files without an entry are exactly the not-committed
        # crash state resume already handles), never the job.
        try:
            manifest.commit(
                out_dir, record,
                write_bytes=lambda name, data: writer.write_file(
                    name, data, shard.index
                ),
                name=own_name,
            )
        except ShardWriteError as e:
            fail(e)
            return
        report.committed += 1
        report.lines += acc.lines
        report.rows += record.rows
        report.rejects += record.rejects
        report.payload_bytes += acc.payload_bytes
        reg.increment("job_shards_committed_total")
        if c_span is not None:
            c_span.end(outcome="committed", rows=record.rows,
                       lines=acc.lines)
        # Reject accounting lands at COMMIT time: the counter equals
        # lines durably present in reject tables, exactly — a failed
        # shard's rejects never count, a replayed shard's count once.
        for reason, n in acc.reason_counts.items():
            report.reject_reasons[reason] = (
                report.reject_reasons.get(reason, 0) + n
            )
            reg.increment("job_rejected_lines_total", n,
                          labels={"reason": reason})

    current: Optional[int] = None
    acc = _ShardAccumulator()
    commits_this_run = 0

    def _advance_to(pool_idx: Optional[int]) -> bool:
        """Commit the current shard and any EMPTY shards (no batches)
        between it and ``pool_idx`` (None = end of stream).  Returns
        False when the stop_after_shards budget ran out or the
        preemption stop_event fired — every commit boundary is a legal
        stopping point (the shard just committed stays committed; the
        manifest resumes exactly)."""
        nonlocal current, acc, commits_this_run
        end = pool_idx if pool_idx is not None else len(pool_shards)
        while current is not None and current < end:
            _commit(current, acc)
            acc = _ShardAccumulator()
            commits_this_run += 1
            if (policy.stop_after_shards is not None
                    and commits_this_run >= policy.stop_after_shards):
                return False
            if (policy.stop_event is not None
                    and policy.stop_event.is_set()
                    # Only with work still pending: a notice landing on
                    # the FINAL commit must not turn a finished run
                    # into a preempted one (the relaunch would be a
                    # pure no-op and the report would read incomplete).
                    and (pool_idx is not None or current + 1 < end)):
                report.preempted = True
                reg.increment("job_preempted_total")
                LOG.warning(
                    "job: preemption stop (SIGTERM) honored at the "
                    "shard %d commit boundary — resume re-parses "
                    "nothing committed", remaining[current].index,
                )
                return False
            current += 1
        current = end if pool_idx is not None else None
        return True

    try:
        if agg_spec is not None:
            stream = parser.aggregate_batch_stream(
                _tap(pool.batches(detach=True)), agg_spec,
            )
        else:
            stream = parser.parse_batch_stream(
                _tap(pool.batches(detach=True)), emit_views=False,
            )
        for result in stream:
            pshard, bidx, n_lines, src_bytes = meta.popleft()
            if current is None:
                current = 0
            if pshard != current and not _advance_to(pshard):
                report.stopped_early = True
                return report
            if agg_spec is not None:
                _fold_outcome(remaining[pshard], bidx, src_bytes, result,
                              acc)
            else:
                _fold_result(remaining[pshard], bidx, src_bytes, result,
                             acc, reg)
        if current is None and pool_shards:
            current = 0  # every shard was empty
        if not _advance_to(None):
            report.stopped_early = True
            return report
    finally:
        pool.close()
        if armed_caller_parser:
            # Hand the caller's parser back as received: the job's
            # injections must not outlive it, and a chaos plan the
            # caller had armed BEFORE the job must survive it.
            try:
                parser.arm_device_chaos(prior_device_chaos)
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log_warning_once(LOG, f"job: chaos disarm failed: {e}")
        if own_parser:
            # A parser built here is ours to release: its oracle worker
            # pool / assembly threads must not outlive the job (a
            # caller looping run_job would otherwise accumulate pools).
            try:
                parser.close()
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log_warning_once(LOG, f"job: parser close failed: {e}")
        report.wall_s = time.perf_counter() - t_start
        if feed_state["span"] is not None:
            feed_state["span"].end()
        if job_span is not None:
            job_span.end(committed=report.committed,
                         skipped=report.skipped,
                         preempted=report.preempted)
    return report


def _fold_outcome(shard: Shard, batch_index: int, src_bytes: int,
                  outcome, acc: _ShardAccumulator) -> None:
    """Aggregate-mode twin of :func:`_fold_result`: merge one
    :class:`~logparser_tpu.analytics.state.AggregateOutcome` into its
    shard's accumulator — partial state, good-line count, and the same
    reasoned reject ledger the row path lands (an aggregate job never
    silently drops a bad line either)."""
    line_base = acc.lines
    if acc.agg is None:
        acc.agg = outcome.state
    else:
        acc.agg.merge(outcome.state)
    acc.rows += outcome.good_lines
    for row, reason, raw in outcome.reject_items:
        acc.rejects.append((
            shard.index, batch_index, line_base + int(row), reason,
            bytes(raw),
        ))
        acc.reason_counts[reason] = acc.reason_counts.get(reason, 0) + 1
    acc.lines += outcome.lines_read
    acc.payload_bytes += int(src_bytes)


def _fold_result(shard: Shard, batch_index: int, src_bytes: int, result,
                 acc: _ShardAccumulator, reg) -> None:
    """Fold one BatchResult into its shard's accumulator: the valid
    rows' Arrow table (copy mode — the file outlives the batch buffers)
    and one reject row per invalid line, reasoned and raw."""
    import pyarrow as pa

    line_base = acc.lines
    valid = np.asarray(result.valid[:result.lines_read], dtype=bool)
    if result.lines_read:
        table = result.to_arrow(include_validity=False, strings="copy")
        if not valid.all():
            table = table.filter(pa.array(valid))
        if table.num_rows:
            acc.tables.append(table)
    for i in sorted(result.reject_reasons):
        reason = result.reject_reasons[i]
        acc.rejects.append((
            shard.index, batch_index, line_base + i, reason,
            bytes(result.raw_line(i)),
        ))
        acc.reason_counts[reason] = acc.reason_counts.get(reason, 0) + 1
    n_rej = int(np.count_nonzero(~valid))
    if n_rej != len(result.reject_reasons):
        # Defensive: every invalid row must carry a reason — a drift
        # here means a new reject path forgot the ledger.  Surface it
        # loudly (counted + warned, STATIC warn-once key; the counts
        # ride DEBUG), still never a raise.
        log_warning_once(
            LOG,
            "job: invalid rows without reject reasons in a batch "
            "(reject ledger drifted; job_reject_ledger_drift_total "
            "counts batches, details at DEBUG)",
        )
        LOG.debug("job: ledger drift on shard %d batch %d: %d invalid "
                  "rows, %d reasons", shard.index, batch_index, n_rej,
                  len(result.reject_reasons))
        reg.increment("job_reject_ledger_drift_total")
    acc.lines += result.lines_read
    acc.payload_bytes += int(src_bytes)
