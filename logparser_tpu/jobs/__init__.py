"""Durable batch jobs: corpus -> sharded Arrow files, exactly once.

The batch tier's durability layer (docs/JOBS.md).  ``run_job`` parses
multi-GB corpora through the feeder fabric + device pipeline into
per-shard Arrow IPC files with a JSON manifest as the commit log:
crash-resumable (committed shards are never re-parsed; the merged
output of a killed-and-resumed run is byte-identical to an undisturbed
one), with a first-class per-line reject channel (per-shard error
tables, ``job_rejected_lines_total{reason}``) and writer I/O fault
tolerance (bounded retry, shard-level failure isolation).

CLI: ``python -m logparser_tpu.jobs`` (see ``--help``).
"""
from .manifest import (  # noqa: F401
    MANIFEST_NAME,
    JobManifest,
    ManifestError,
    ShardRecord,
    committed_anywhere,
    host_manifest_name,
    list_host_manifests,
    merge_manifests,
)
from .runner import (  # noqa: F401
    EXIT_PREEMPTED,
    JobPolicy,
    JobReport,
    JobSpec,
    run_job,
)
from .writer import (  # noqa: F401
    JobWriter,
    ShardWriteError,
    build_reject_table,
    leaked_temp_files,
    merged_hash,
    merged_job_aggregate,
    reject_schema,
    sweepable_temp_files,
)
