// Minimal C++ client of the sidecar wire protocol (docs/PROTOCOL.md).
//
// The protocol's whole point is that a non-Python engine can implement it
// in an afternoon: u32 big-endian length-prefixed frames, one CONFIG JSON
// frame, then [LINES frame -> ARROW frame] pairs, 0xFFFFFFFF marker +
// error frame for structured errors (BUSY / DEADLINE / plain), length-0
// frame to end the session.  This file is that afternoon, kept to plain
// POSIX sockets + C++17 so the in-image toolchain builds it exactly like
// native/logframe.cc (g++, no third-party deps; the Arrow IPC payload is
// received and byte-checked, not decoded — decoding is pyarrow's job in
// the smoke tests that assert byte-parity against the golden vectors).
//
// Modes:
//   --replay FILE   send FILE's bytes verbatim (a golden request vector),
//                   read responses until EOF; --dump-prefix writes each
//                   ARROW payload to PREFIX<k>.bin.  Prints one JSON line:
//                   {"arrow":n,"errors":m,"bytes":total}.
//   --config FILE --lines FILE
//                   build the CONFIG frame from FILE's JSON bytes and ONE
//                   LINES frame from FILE's newline-delimited text (one
//                   trailing '\n' stripped; count = line count), send it
//                   --repeat times or for --duration seconds, classify
//                   every response (ok / busy / deadline / error / reset),
//                   optionally --dump the first ARROW payload.  Prints one
//                   JSON line with outcome counts + per-request latencies
//                   in ms — the shape tools/loadgen.py merges as its
//                   native fast-driver (one process per client).
//
// Build (done on demand by logparser_tpu.native.build_tool):
//   g++ -O2 -std=c++17 -pthread svc_client.cc -o svc_client

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kErrorMarker = 0xFFFFFFFFu;

double now_s() {
  using clock = std::chrono::steady_clock;
  return std::chrono::duration<double>(clock::now().time_since_epoch())
      .count();
}

bool send_all(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, 0);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// 0 = clean EOF at a frame boundary, -1 = reset/mid-buffer EOF, 1 = ok.
int recv_exact(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r == 0) return got == 0 ? 0 : -1;
    if (r < 0) return -1;
    got += static_cast<size_t>(r);
  }
  return 1;
}

bool send_frame(int fd, const std::string& payload) {
  uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  return send_all(fd, &len, 4) &&
         send_all(fd, payload.data(), payload.size());
}

// kind: 1 ARROW payload, 2 error text, 0 clean EOF, -1 reset.
int recv_response(int fd, std::string* payload) {
  uint32_t be = 0;
  int rc = recv_exact(fd, &be, 4);
  if (rc <= 0) return rc;
  uint32_t len = ntohl(be);
  bool is_error = (len == kErrorMarker);
  if (is_error) {
    rc = recv_exact(fd, &be, 4);
    if (rc <= 0) return -1;
    len = ntohl(be);
  }
  payload->resize(len);
  if (len > 0 && recv_exact(fd, payload->data(), len) <= 0) return -1;
  return is_error ? 2 : 1;
}

int dial(const std::string& host, const std::string& port) {
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

bool read_file(const std::string& path, std::string* out) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  out->assign(std::istreambuf_iterator<char>(f),
              std::istreambuf_iterator<char>());
  return true;
}

bool write_file(const std::string& path, const std::string& data) {
  std::ofstream f(path, std::ios::binary);
  if (!f) return false;
  f.write(data.data(), static_cast<std::streamsize>(data.size()));
  return static_cast<bool>(f);
}

// One LINES frame payload from newline-delimited text: strip ONE trailing
// '\n' (the framing joins lines WITH '\n', it does not terminate), count
// the lines, prefix the u32 BE count (docs/PROTOCOL.md "LINES frame").
std::string lines_payload(std::string text) {
  if (!text.empty() && text.back() == '\n') text.pop_back();
  // "" (and a lone "\n" after the strip) -> zero lines; the drivers
  // never ship empty corpora.
  uint32_t count = text.empty() ? 0 : 1;
  for (char c : text)
    if (c == '\n') ++count;
  uint32_t be = htonl(count);
  std::string payload(reinterpret_cast<const char*>(&be), 4);
  payload += text;
  return payload;
}

int run_replay(const std::string& host, const std::string& port,
               const std::string& replay_path,
               const std::string& dump_prefix) {
  std::string request;
  if (!read_file(replay_path, &request)) {
    std::fprintf(stderr, "cannot read %s\n", replay_path.c_str());
    return 2;
  }
  int fd = dial(host, port);
  if (fd < 0) {
    std::fprintf(stderr, "connect failed\n");
    return 2;
  }
  if (!send_all(fd, request.data(), request.size())) {
    std::fprintf(stderr, "send failed\n");
    ::close(fd);
    return 2;
  }
  size_t arrow = 0, errors = 0, bytes = 0;
  std::string payload;
  int rc;
  while ((rc = recv_response(fd, &payload)) > 0) {
    bytes += payload.size();
    if (rc == 1) {
      if (!dump_prefix.empty()) {
        write_file(dump_prefix + std::to_string(arrow) + ".bin", payload);
      }
      ++arrow;
    } else {
      ++errors;
    }
  }
  ::close(fd);
  if (rc < 0) {
    std::fprintf(stderr, "connection reset mid-frame\n");
    return 2;
  }
  std::printf("{\"arrow\":%zu,\"errors\":%zu,\"bytes\":%zu}\n", arrow,
              errors, bytes);
  return 0;
}

struct DriveStats {
  size_t ok = 0, busy = 0, deadline = 0, errors = 0, resets = 0;
  size_t lines_ok = 0, arrow_bytes = 0;
  std::vector<double> latencies_s;
};

int run_drive(const std::string& host, const std::string& port,
              const std::string& config_path, const std::string& lines_path,
              long repeat, double duration_s, const std::string& dump_path) {
  std::string config, text;
  if (!read_file(config_path, &config) || !read_file(lines_path, &text)) {
    std::fprintf(stderr, "cannot read config/lines file\n");
    return 2;
  }
  std::string payload = lines_payload(std::move(text));
  uint32_t count_be;
  std::memcpy(&count_be, payload.data(), 4);
  uint32_t line_count = ntohl(count_be);

  auto connect = [&]() -> int {
    int fd = dial(host, port);
    if (fd >= 0 && !send_frame(fd, config)) {
      ::close(fd);
      return -1;
    }
    return fd;
  };
  int fd = connect();
  if (fd < 0) {
    std::fprintf(stderr, "connect failed\n");
    return 2;
  }
  DriveStats st;
  std::string response;
  bool dumped = false;
  double stop_at = duration_s > 0 ? now_s() + duration_s : 0.0;
  for (long i = 0; repeat <= 0 || i < repeat; ++i) {
    if (stop_at > 0 && now_s() >= stop_at) break;
    double t0 = now_s();
    if (!send_frame(fd, payload)) {
      ++st.resets;
      break;
    }
    int rc = recv_response(fd, &response);
    if (rc <= 0) {
      ++st.resets;
      break;
    }
    if (rc == 1) {
      ++st.ok;
      st.lines_ok += line_count;
      st.arrow_bytes += response.size();
      st.latencies_s.push_back(now_s() - t0);
      if (!dumped && !dump_path.empty()) {
        write_file(dump_path, response);
        dumped = true;
      }
    } else if (response.rfind("BUSY", 0) == 0) {
      ++st.busy;
      // Session-level sheds (reason sessions/draining) close this
      // connection BY CONTRACT (docs/PROTOCOL.md "Overload responses"):
      // reconnect before the next request so the shed never reads as a
      // reset.
      if (response.find("\"reason\":\"sessions\"") != std::string::npos ||
          response.find("\"reason\":\"draining\"") != std::string::npos) {
        ::close(fd);
        fd = connect();
        if (fd < 0) break;
      }
    } else if (response.rfind("DEADLINE", 0) == 0) {
      ++st.deadline;
    } else {
      ++st.errors;
    }
  }
  if (fd < 0) {
    // Reconnect after a session shed failed: report what we have.
    std::fprintf(stderr, "reconnect after session shed failed\n");
  }
  // End of session: length-0 frame, then close.
  if (fd >= 0) {
    uint32_t zero = 0;
    send_all(fd, &zero, 4);
    ::close(fd);
  }

  std::string lat = "[";
  char buf[32];
  for (size_t i = 0; i < st.latencies_s.size(); ++i) {
    std::snprintf(buf, sizeof(buf), "%s%.3f", i ? "," : "",
                  st.latencies_s[i] * 1000.0);
    lat += buf;
  }
  lat += "]";
  std::printf(
      "{\"ok\":%zu,\"busy\":%zu,\"deadline\":%zu,\"errors\":%zu,"
      "\"resets\":%zu,\"lines_ok\":%zu,\"arrow_bytes\":%zu,"
      "\"latencies_ms\":%s}\n",
      st.ok, st.busy, st.deadline, st.errors, st.resets, st.lines_ok,
      st.arrow_bytes, lat.c_str());
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string host = "127.0.0.1", port, config, lines, replay;
  std::string dump, dump_prefix;
  long repeat = 1;
  double duration_s = 0.0;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&](const char* what) -> std::string {
      if (i + 1 >= argc) {
        std::fprintf(stderr, "missing value for %s\n", what);
        std::exit(2);
      }
      return argv[++i];
    };
    if (a == "--host") host = next("--host");
    else if (a == "--port") port = next("--port");
    else if (a == "--config") config = next("--config");
    else if (a == "--lines") lines = next("--lines");
    else if (a == "--replay") replay = next("--replay");
    else if (a == "--repeat") repeat = std::stol(next("--repeat"));
    else if (a == "--duration") duration_s = std::stod(next("--duration"));
    else if (a == "--dump") dump = next("--dump");
    else if (a == "--dump-prefix") dump_prefix = next("--dump-prefix");
    else {
      std::fprintf(stderr, "unknown argument %s\n", a.c_str());
      return 2;
    }
  }
  if (port.empty()) {
    std::fprintf(stderr,
                 "usage: svc_client --port P [--host H] "
                 "(--replay FILE [--dump-prefix P] | "
                 "--config FILE --lines FILE [--repeat N | --duration S] "
                 "[--dump FILE])\n");
    return 2;
  }
  if (!replay.empty()) return run_replay(host, port, replay, dump_prefix);
  if (config.empty() || lines.empty()) {
    std::fprintf(stderr, "--config and --lines are required\n");
    return 2;
  }
  if (duration_s > 0) {
    repeat = 0;  // duration bounds the loop instead
  } else if (repeat <= 0) {
    repeat = 1;  // neither bound given: one shot, never a zero-run
  }
  return run_drive(host, port, config, lines, repeat, duration_s, dump);
}
