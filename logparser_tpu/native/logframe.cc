// Host-side batch framing: newline-delimited log bytes -> padded [B, L]
// uint8 buffers + lengths, the wire format of the TPU split pipeline
// (logparser_tpu/tpu/runtime.py encode_batch).
//
// This is the rebuild's native data-loader tier.  The reference has no
// native code (SURVEY.md §2: 100% Java; its line framing lives in Hadoop's
// LineRecordReader, httpdlog-inputformat/.../ApacheHttpdLogfileRecordReader
// .java:57) — here the framing + packing loop is the host hot path feeding
// the chip, so it is C++ with a pthread fan-out over row ranges, exposed to
// Python via ctypes (no pybind11 in the image).
//
// Line semantics match the reader: lines split on '\n', a trailing '\r' is
// stripped (CRLF tolerance), a final unterminated line counts.  Lines longer
// than L are truncated in the buffer and reported through the per-line
// lengths array as (L | LP_OVERFLOW_BIT) — the flag marks the row for the
// host oracle path; the stored length is the truncated one.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

const int32_t LP_OVERFLOW_BIT = 1 << 30;

// Pass 1: count lines and the maximum line length (bucket selection).
void lp_scan(const uint8_t* data, int64_t size,
             int64_t* n_lines, int64_t* max_len) {
  int64_t lines = 0, maxlen = 0, start = 0;
  for (int64_t i = 0; i <= size; ++i) {
    if (i == size || data[i] == '\n') {
      if (i == size && i == start) break;  // no trailing fragment
      int64_t end = i;
      if (end > start && data[end - 1] == '\r') --end;
      ++lines;
      maxlen = std::max(maxlen, end - start);
      start = i + 1;
    }
  }
  *n_lines = lines;
  *max_len = maxlen;
}

// Frame into offsets (line starts) + lens.  Returns the number of lines.
int64_t lp_frame(const uint8_t* data, int64_t size,
                 int64_t* offsets, int32_t* lens, int64_t max_lines) {
  int64_t n = 0, start = 0;
  for (int64_t i = 0; i <= size && n < max_lines; ++i) {
    if (i == size || data[i] == '\n') {
      if (i == size && i == start) break;
      int64_t end = i;
      if (end > start && data[end - 1] == '\r') --end;
      offsets[n] = start;
      lens[n] = static_cast<int32_t>(end - start);
      ++n;
      start = i + 1;
    }
  }
  return n;
}

// Pack framed lines into a padded [n, L] uint8 buffer (zero-filled) +
// lengths with the overflow bit for truncated lines.  Multi-threaded over
// row ranges.
void lp_pack(const uint8_t* data, const int64_t* offsets,
             const int32_t* lens, int64_t n,
             uint8_t* out, int32_t* lengths, int64_t L, int32_t threads) {
  if (threads < 1) threads = 1;
  int64_t chunk = (n + threads - 1) / threads;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t len = lens[r];
      uint8_t* row = out + r * L;
      if (len > L) {
        std::memcpy(row, data + offsets[r], L);
        lengths[r] = static_cast<int32_t>(L) | LP_OVERFLOW_BIT;
      } else {
        std::memcpy(row, data + offsets[r], len);
        std::memset(row + len, 0, L - len);
        lengths[r] = static_cast<int32_t>(len);
      }
    }
  };
  if (threads == 1 || n < 4096) {
    work(0, n);
    return;
  }
  std::vector<std::thread> pool;
  for (int32_t t = 0; t < threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi);
  }
  for (auto& th : pool) th.join();
}

// Span gather: per-row (start, end) windows of a padded [B, L] buffer ->
// one flat byte stream at precomputed destination offsets.  The inverse of
// lp_pack — it materializes device span columns (string fields) for
// non-Arrow consumers without a per-row Python loop.  Rows with
// offsets[r] == offsets[r+1] (invalid/null/empty) copy nothing.
void lp_gather_spans(const uint8_t* buf, int64_t B, int64_t L,
                     const int32_t* starts, const int64_t* offsets,
                     uint8_t* out, int32_t threads) {
  if (threads < 1) threads = 1;
  int64_t chunk = (B + threads - 1) / threads;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t len = offsets[r + 1] - offsets[r];
      if (len <= 0) continue;
      std::memcpy(out + offsets[r], buf + r * L + starts[r], len);
    }
  };
  if (threads == 1 || B < 4096) {
    work(0, B);
    return;
  }
  std::vector<std::thread> pool;
  for (int32_t t = 0; t < threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(B, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi);
  }
  for (auto& th : pool) th.join();
}

// Multi-column span gather: K span columns over the SAME [B, L] buffer in
// one threaded fan-out, amortizing the thread-pool spawn across columns
// (the Arrow bridge materializes every string column of a batch at once).
// `starts` is [K*B] laid out column-major (column k's rows begin at k*B);
// `offsets` is [K*B+1] cumulative over that layout, so each column's bytes
// land contiguously in `out` and Python can slice per-column views
// zero-copy.
void lp_gather_spans_multi(const uint8_t* buf, int64_t B, int64_t L,
                           const int32_t* starts, const int64_t* offsets,
                           uint8_t* out, int64_t K, int32_t threads) {
  if (threads < 1) threads = 1;
  int64_t n = K * B;
  int64_t chunk = (n + threads - 1) / threads;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t len = offsets[i + 1] - offsets[i];
      if (len <= 0) continue;
      int64_t r = i % B;
      std::memcpy(out + offsets[i], buf + r * L + starts[i], len);
    }
  };
  if (threads == 1 || n < 4096) {
    work(0, n);
    return;
  }
  std::vector<std::thread> pool;
  for (int32_t t = 0; t < threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi);
  }
  for (auto& th : pool) th.join();
}

// Flat re-layout: per-row copy from arbitrary source offsets in one flat
// byte buffer to contiguous destination offsets.  The Arrow bridge's
// URI-repair splice uses it to rebuild a column after patching rows
// (numpy's fancy-index gather is per-element; this is memcpy-speed).
void lp_copy_spans(const uint8_t* src, const int64_t* src_off,
                   uint8_t* dst, const int64_t* dst_off,
                   int64_t n, int32_t threads) {
  if (threads < 1) threads = 1;
  int64_t chunk = (n + threads - 1) / threads;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t len = dst_off[r + 1] - dst_off[r];
      if (len <= 0) continue;
      std::memcpy(dst + dst_off[r], src + src_off[r], len);
    }
  };
  if (threads == 1 || n < 4096) {
    work(0, n);
    return;
  }
  std::vector<std::thread> pool;
  for (int32_t t = 0; t < threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi);
  }
  for (auto& th : pool) th.join();
}

// One-shot convenience: frame + pack a whole blob.  Returns line count.
int64_t lp_frame_pack(const uint8_t* data, int64_t size,
                      uint8_t* out, int32_t* lengths,
                      int64_t max_lines, int64_t L, int32_t threads) {
  std::vector<int64_t> offsets(max_lines);
  std::vector<int32_t> lens(max_lines);
  int64_t n = lp_frame(data, size, offsets.data(), lens.data(), max_lines);
  lp_pack(data, offsets.data(), lens.data(), n, out, lengths, L, threads);
  return n;
}

}  // extern "C"
