// Host-side batch framing: newline-delimited log bytes -> padded [B, L]
// uint8 buffers + lengths, the wire format of the TPU split pipeline
// (logparser_tpu/tpu/runtime.py encode_batch).
//
// This is the rebuild's native data-loader tier.  The reference has no
// native code (SURVEY.md §2: 100% Java; its line framing lives in Hadoop's
// LineRecordReader, httpdlog-inputformat/.../ApacheHttpdLogfileRecordReader
// .java:57) — here the framing + packing loop is the host hot path feeding
// the chip, so it is C++ with a pthread fan-out over row ranges, exposed to
// Python via ctypes (no pybind11 in the image).
//
// Line semantics match the reader: lines split on '\n', a trailing '\r' is
// stripped (CRLF tolerance), a final unterminated line counts.  Lines longer
// than L are truncated in the buffer and reported through the per-line
// lengths array as (L | LP_OVERFLOW_BIT) — the flag marks the row for the
// host oracle path; the stored length is the truncated one.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <functional>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

#if defined(__SSE2__)
#include <emmintrin.h>
#endif

// Persistent worker pool: per-call std::thread spawns (~50us each) used
// to dominate the small batched calls (view builds, repairs) — the pool
// is created on first parallel call and reused for every lp_* entry
// point.  One job at a time (outer job mutex); chunks are handed out via
// an atomic cursor so uneven rows balance.
namespace {

class Pool {
 public:
  explicit Pool(int n) : nworkers_(n) {
    for (int i = 0; i < n; ++i) workers_.emplace_back([this] { Loop(); });
  }

  void Run(int64_t total, int64_t chunk,
           const std::function<void(int64_t, int64_t)>& body) {
    std::lock_guard<std::mutex> job(job_m_);
    {
      std::lock_guard<std::mutex> lk(m_);
      body_ = &body;
      total_ = total;
      chunk_ = chunk;
      next_.store(0, std::memory_order_relaxed);
      active_.store(nworkers_, std::memory_order_relaxed);
      ++gen_;
      cv_.notify_all();
    }
    std::unique_lock<std::mutex> lk(m_);
    done_cv_.wait(lk, [&] { return active_.load() == 0; });
  }

 private:
  void Loop() {
    uint64_t seen = 0;
    for (;;) {
      const std::function<void(int64_t, int64_t)>* body;
      int64_t total, chunk;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_.wait(lk, [&] { return gen_ != seen; });
        seen = gen_;
        body = body_;
        total = total_;
        chunk = chunk_;
      }
      for (;;) {
        int64_t lo = next_.fetch_add(chunk, std::memory_order_relaxed);
        if (lo >= total) break;
        (*body)(lo, std::min(total, lo + chunk));
      }
      if (active_.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk(m_);
        done_cv_.notify_all();
      }
    }
  }

  int nworkers_;
  std::vector<std::thread> workers_;
  std::mutex job_m_, m_;
  std::condition_variable cv_, done_cv_;
  const std::function<void(int64_t, int64_t)>* body_ = nullptr;
  int64_t total_ = 0, chunk_ = 0;
  std::atomic<int64_t> next_{0};
  std::atomic<int> active_{0};
  uint64_t gen_ = 0;
};

// `weight` = relative per-unit cost (default 1): callers whose units do
// K-times the work (e.g. the row-major view builder, K columns per row)
// pass it so the go-parallel cutoff and chunk size reflect actual work,
// not unit count — lp_run(B, ...) with K=12 columns must not fall into
// the small-n single-thread path that lp_run(K*B, ...) would have
// cleared.
void lp_run(int64_t n, int32_t threads,
            const std::function<void(int64_t, int64_t)>& body,
            int64_t weight = 1) {
  if (weight < 1) weight = 1;
  if (threads <= 1 || n * weight < 4096) {
    body(0, n);
    return;
  }
  static Pool* pool = nullptr;
  static pid_t pool_pid = 0;
  static std::mutex create_m;
  {
    std::lock_guard<std::mutex> lk(create_m);
    if (pool == nullptr || pool_pid != getpid()) {
      // Size by the hardware, not the first caller's thread count — the
      // pool is process-wide and a small first request must not cap
      // every later call's parallelism.  A fork() child inherits the
      // pointer but none of the worker threads (waiting on it would
      // deadlock) — detect by pid and build a fresh pool; the stale
      // object is deliberately leaked (its threads do not exist here).
      unsigned hw = std::thread::hardware_concurrency();
      int n = std::max<int>(threads, hw ? static_cast<int>(hw) : threads);
      pool = new Pool(n);
      pool_pid = getpid();
    }
  }
  int64_t chunk = std::max<int64_t>(
      std::max<int64_t>(1, 512 / weight), n / (threads * 4));
  pool->Run(n, chunk, body);
}

}  // namespace

extern "C" {

const int32_t LP_OVERFLOW_BIT = 1 << 30;

// Pass 1: count lines and the maximum line length (bucket selection).
void lp_scan(const uint8_t* data, int64_t size,
             int64_t* n_lines, int64_t* max_len) {
  int64_t lines = 0, maxlen = 0, start = 0;
  for (int64_t i = 0; i <= size; ++i) {
    if (i == size || data[i] == '\n') {
      if (i == size && i == start) break;  // no trailing fragment
      int64_t end = i;
      if (end > start && data[end - 1] == '\r') --end;
      ++lines;
      maxlen = std::max(maxlen, end - start);
      start = i + 1;
    }
  }
  *n_lines = lines;
  *max_len = maxlen;
}

// Frame into offsets (line starts) + lens.  Returns the number of lines.
int64_t lp_frame(const uint8_t* data, int64_t size,
                 int64_t* offsets, int32_t* lens, int64_t max_lines) {
  int64_t n = 0, start = 0;
  for (int64_t i = 0; i <= size && n < max_lines; ++i) {
    if (i == size || data[i] == '\n') {
      if (i == size && i == start) break;
      int64_t end = i;
      if (end > start && data[end - 1] == '\r') --end;
      offsets[n] = start;
      lens[n] = static_cast<int32_t>(end - start);
      ++n;
      start = i + 1;
    }
  }
  return n;
}

// Pack framed lines into a padded [n, L] uint8 buffer (zero-filled) +
// lengths with the overflow bit for truncated lines.  Multi-threaded over
// row ranges.
void lp_pack(const uint8_t* data, const int64_t* offsets,
             const int32_t* lens, int64_t n,
             uint8_t* out, int32_t* lengths, int64_t L, int32_t threads) {
  if (threads < 1) threads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t len = lens[r];
      uint8_t* row = out + r * L;
      if (len > L) {
        std::memcpy(row, data + offsets[r], L);
        lengths[r] = static_cast<int32_t>(L) | LP_OVERFLOW_BIT;
      } else {
        std::memcpy(row, data + offsets[r], len);
        std::memset(row + len, 0, L - len);
        lengths[r] = static_cast<int32_t>(len);
      }
    }
  };
  lp_run(n, threads, work);
}

// Span gather: per-row (start, end) windows of a padded [B, L] buffer ->
// one flat byte stream at precomputed destination offsets.  The inverse of
// lp_pack — it materializes device span columns (string fields) for
// non-Arrow consumers without a per-row Python loop.  Rows with
// offsets[r] == offsets[r+1] (invalid/null/empty) copy nothing.
void lp_gather_spans(const uint8_t* buf, int64_t B, int64_t L,
                     const int32_t* starts, const int64_t* offsets,
                     uint8_t* out, int32_t threads) {
  if (threads < 1) threads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t len = offsets[r + 1] - offsets[r];
      if (len <= 0) continue;
      std::memcpy(out + offsets[r], buf + r * L + starts[r], len);
    }
  };
  lp_run(B, threads, work);
}

// Multi-column span gather: K span columns over the SAME [B, L] buffer in
// one threaded fan-out, amortizing the thread-pool spawn across columns
// (the Arrow bridge materializes every string column of a batch at once).
// `starts` is [K*B] laid out column-major (column k's rows begin at k*B);
// `offsets` is [K*B+1] cumulative over that layout, so each column's bytes
// land contiguously in `out` and Python can slice per-column views
// zero-copy.
void lp_gather_spans_multi(const uint8_t* buf, int64_t B, int64_t L,
                           const int32_t* starts, const int64_t* offsets,
                           uint8_t* out, int64_t K, int32_t threads) {
  if (threads < 1) threads = 1;
  int64_t n = K * B;
  if (n == 0) return;  // the row-tracking modulo below needs B > 0
  auto work = [&](int64_t lo, int64_t hi) {
    int64_t r = lo % B;
    int64_t row_base = r * L;
    for (int64_t i = lo; i < hi; ++i) {
      int64_t len = offsets[i + 1] - offsets[i];
      if (len > 0) {
        std::memcpy(out + offsets[i], buf + row_base + starts[i], len);
      }
      if (++r == B) { r = 0; row_base = 0; } else row_base += L;
    }
  };
  lp_run(n, threads, work);
}

// Flat re-layout: per-row copy from arbitrary source offsets in one flat
// byte buffer to contiguous destination offsets.  The Arrow bridge's
// URI-repair splice uses it to rebuild a column after patching rows
// (numpy's fancy-index gather is per-element; this is memcpy-speed).
void lp_copy_spans(const uint8_t* src, const int64_t* src_off,
                   uint8_t* dst, const int64_t* dst_off,
                   int64_t n, int32_t threads) {
  if (threads < 1) threads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t len = dst_off[r + 1] - dst_off[r];
      if (len <= 0) continue;
      std::memcpy(dst + dst_off[r], src + src_off[r], len);
    }
  };
  lp_run(n, threads, work);
}

// Scatter variant of lp_copy_spans: explicit per-row lengths and a
// caller-provided destination, so subsets of rows can be written into a
// shared side buffer at non-contiguous offsets (the view assembler lays
// clean and repaired rows into ONE allocation instead of copy+concat+
// recopy rounds).
void lp_scatter_spans(const uint8_t* src, const int64_t* src_off,
                      const int64_t* lens, uint8_t* dst,
                      const int64_t* dst_off, int64_t n, int32_t threads) {
  if (threads < 1) threads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t len = lens[r];
      if (len <= 0) continue;
      std::memcpy(dst + dst_off[r], src + src_off[r], len);
    }
  };
  lp_run(n, threads, work);
}

// Arrow BinaryView (string_view) materializer: K span columns over the
// same [B, L] buffer -> packed 16-byte Arrow view structs, NO byte
// gather.  Strings of <= 12 bytes are inlined in the view (the Arrow
// spec requires it); longer ones store (length, 4-byte prefix,
// buffer_index=0, offset into the flattened [B*L] buffer), so the Arrow
// column references the batch buffer zero-copy.  starts/lens are [K*B]
// column-major; lens[i] < 0 marks a null row (zeroed view; the validity
// bitmap is the caller's).  Offsets require B*L < 2^31 (caller-guarded).
void lp_build_views(const uint8_t* buf, int64_t B, int64_t L,
                    const int32_t* starts, const int32_t* lens,
                    uint8_t* views, int64_t K, int32_t threads) {
  if (threads < 1) threads = 1;
  int64_t n = K * B;
  if (n == 0) return;  // the row-tracking modulo below needs B > 0
  int64_t size = B * L;
#if !defined(__SSE2__)
  // Inline masks: keep bytes < len of a constant-size 12-byte load
  // (branch-free tail zeroing; the variable-length memcpy + memset pair
  // was the single-core hot spot).  Scalar build only — the SSE2 path
  // has its own 16-byte mask table.
  static uint64_t mask_a[13];
  static uint32_t mask_b[13];
  static bool masks_init = [] {
    for (int l = 0; l <= 12; ++l) {
      int ka = l < 8 ? l : 8;
      int kb = l < 8 ? 0 : l - 8;
      mask_a[l] = ka == 8 ? ~0ULL : ((1ULL << (8 * ka)) - 1);
      mask_b[l] = kb == 4 ? ~0U : ((1U << (8 * kb)) - 1);
    }
    return true;
  }();
  (void)masks_init;
#endif
  // ROW-major traversal (rows outer, columns inner): all K columns of a
  // row resolve while that row's line bytes sit in L1.  The flat
  // column-major loop re-streamed the whole [B, L] buffer once per
  // column — at 16k x 384 (6.3 MB, beyond L2) that made the builder
  // ~4x slower from cache misses alone (measured 1.27 ms vs 0.31 ms for
  // an L1-resident buffer).  starts/lens reads and view writes become
  // K strided streams (B elements apart), which prefetch fine.
#if defined(__SSE2__)
  // 16-byte masks for the SSE path: bytes 4..3+l set, bytes 0..3 clear
  // (the length lane is OR'd in separately).
  alignas(16) static uint8_t mask16[13][16];
  static bool mask16_init = [] {
    for (int l = 0; l <= 12; ++l)
      for (int b = 0; b < 16; ++b)
        mask16[l][b] = (b >= 4 && b < 4 + l) ? 0xFF : 0;
    return true;
  }();
  (void)mask16_init;
#endif
  auto work = [&](int64_t rlo, int64_t rhi) {
    for (int64_t r = rlo; r < rhi; ++r) {
      int64_t row_base = r * L;
      for (int64_t k = 0; k < K; ++k) {
        int64_t i = k * B + r;
        uint8_t* v = views + i * 16;
        int32_t len = lens[i];
#if defined(__SSE2__)
        if (len < 0) {
          _mm_storeu_si128(reinterpret_cast<__m128i*>(v),
                           _mm_setzero_si128());
          continue;
        }
        int64_t off = row_base + starts[i];
        const uint8_t* src = buf + off;
        if (len <= 12) {
          __m128i out;
          if (off + 16 <= size) {
            // One 16-byte load — reads up to 16-len bytes past the
            // span, which the off+16<=size guard keeps inside the
            // buffer (do NOT relax it to off+len+4) — then shift the
            // 12 inline bytes into place, mask the tail, OR the
            // length lane.
            __m128i data = _mm_loadu_si128(
                reinterpret_cast<const __m128i*>(src));
            out = _mm_slli_si128(data, 4);
            out = _mm_and_si128(out, *reinterpret_cast<const __m128i*>(
                                         mask16[len]));
            out = _mm_or_si128(out, _mm_cvtsi32_si128(len));
          } else {
            alignas(16) uint8_t tmp[16] = {0};
            std::memcpy(&tmp[0], &len, 4);
            std::memcpy(&tmp[4], src, static_cast<size_t>(len));
            out = _mm_load_si128(reinterpret_cast<const __m128i*>(tmp));
          }
          _mm_storeu_si128(reinterpret_cast<__m128i*>(v), out);
        } else {
          int32_t first4;
          std::memcpy(&first4, src, 4);
          _mm_storeu_si128(
              reinterpret_cast<__m128i*>(v),
              _mm_set_epi32(static_cast<int32_t>(off), 0, first4, len));
        }
#else
        if (len < 0) {
          std::memset(v, 0, 16);
          continue;
        }
        int64_t off = row_base + starts[i];
        const uint8_t* src = buf + off;
        std::memcpy(v, &len, 4);
        if (len <= 12) {
          uint64_t a = 0;
          uint32_t b = 0;
          if (off + 12 <= size) {
            std::memcpy(&a, src, 8);
            std::memcpy(&b, src + 8, 4);
            a &= mask_a[len];
            b &= mask_b[len];
          } else {
            uint8_t tmp[12] = {0};
            std::memcpy(tmp, src, static_cast<size_t>(len));
            std::memcpy(&a, tmp, 8);
            std::memcpy(&b, tmp + 8, 4);
          }
          std::memcpy(v + 4, &a, 8);
          std::memcpy(v + 12, &b, 4);
        } else {
          std::memcpy(v + 4, src, 4);
          int32_t bufi = 0;
          int32_t off32 = static_cast<int32_t>(off);
          std::memcpy(v + 8, &bufi, 4);
          std::memcpy(v + 12, &off32, 4);
        }
#endif
      }
    }
  };
  lp_run(B, threads, work, K);
}

// The Arrow string_view element encoding (one place — lp_patch_views and
// lp_special_write both re-point views at side buffers): <= 12 bytes
// inline zero-padded, longer values as (4-byte prefix, buffer_index,
// offset).
static inline void lp_encode_view(uint8_t* v, const uint8_t* src,
                                  int32_t len, int32_t buffer_index,
                                  int64_t off) {
  std::memcpy(v, &len, 4);
  if (len <= 12) {
    std::memset(v + 4, 0, 12);
    std::memcpy(v + 4, src, static_cast<size_t>(len));
  } else {
    std::memcpy(v + 4, src, 4);
    int32_t off32 = static_cast<int32_t>(off);
    std::memcpy(v + 8, &buffer_index, 4);
    std::memcpy(v + 12, &off32, 4);
  }
}

// Re-point selected rows of a [B, 16] Arrow view array at a side buffer
// (repaired / overridden values).  rows/side_off are per patch entry;
// the same inline-vs-reference encoding as lp_build_views.
void lp_patch_views(const uint8_t* side, const int64_t* side_off,
                    const int64_t* rows, int64_t n_rows,
                    int32_t buffer_index, uint8_t* views) {
  for (int64_t j = 0; j < n_rows; ++j) {
    int64_t off = side_off[j];
    lp_encode_view(views + rows[j] * 16, side + off,
                   static_cast<int32_t>(side_off[j + 1] - off),
                   buffer_index, off);
  }
}

// URI-repair scan (the hot classification of the Arrow bridge's
// _repair_fix_segments, ported 1:1 — see that function's docstring for
// the semantics derivation).  mode 0 = decode (path/userinfo): good %XX
// escapes substitute their byte, bad escapes stay literal; mode 1 =
// escape (query): bad '%' expands to "%25", encode-set bytes to their
// uppercase %XX triple.  Rows with any byte >= 0x80 — or, in decode
// mode, a good escape decoding to >= 0x80 — set py_flags[r] (exact
// UTF-8 semantics stay in Python) and get out_lens[r] = 0.
static inline bool lp_is_hex(uint8_t c) {
  return (c >= '0' && c <= '9') || (c >= 'a' && c <= 'f') ||
         (c >= 'A' && c <= 'F');
}
static inline int lp_hex_val(uint8_t c) {
  if (c <= '9') return c - '0';
  if (c >= 'a') return c - 'a' + 10;
  return c - 'A' + 10;
}

void lp_repair_scan(const uint8_t* seg, const int64_t* seg_off, int64_t n,
                    int32_t mode, const uint8_t* enc_table,
                    int64_t* out_lens, uint8_t* py_flags, int32_t threads) {
  if (threads < 1) threads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const uint8_t* s = seg + seg_off[r];
      int64_t len = seg_off[r + 1] - seg_off[r];
      bool py = false;
      int64_t out = len;
      for (int64_t i = 0; i < len; ++i) {
        uint8_t c = s[i];
        if (c >= 0x80) { py = true; break; }
        if (c == '%' && i + 2 < len && lp_is_hex(s[i + 1]) &&
            lp_is_hex(s[i + 2])) {
          if (mode == 0) {
            int dec = (lp_hex_val(s[i + 1]) << 4) | lp_hex_val(s[i + 2]);
            if (dec >= 0x80) { py = true; break; }
            out -= 2;
            i += 2;  // consume the escape
          }
          // escape mode: well-formed escapes copy verbatim
        } else if (mode == 1 && (c == '%' || enc_table[c])) {
          out += 2;  // %25 insertion / %XX expansion
        }
      }
      py_flags[r] = py ? 1 : 0;
      out_lens[r] = py ? 0 : out;
    }
  };
  lp_run(n, threads, work);
}

void lp_repair_write(const uint8_t* seg, const int64_t* seg_off, int64_t n,
                     int32_t mode, const uint8_t* enc_table,
                     const int64_t* out_off, const uint8_t* py_flags,
                     uint8_t* out, int32_t threads) {
  static const char HEX[] = "0123456789ABCDEF";
  if (threads < 1) threads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      if (py_flags[r]) continue;
      const uint8_t* s = seg + seg_off[r];
      int64_t len = seg_off[r + 1] - seg_off[r];
      uint8_t* d = out + out_off[r];
      for (int64_t i = 0; i < len; ++i) {
        uint8_t c = s[i];
        bool good = c == '%' && i + 2 < len && lp_is_hex(s[i + 1]) &&
                    lp_is_hex(s[i + 2]);
        if (mode == 0) {
          if (good) {
            *d++ = static_cast<uint8_t>(
                (lp_hex_val(s[i + 1]) << 4) | lp_hex_val(s[i + 2]));
            i += 2;
          } else {
            *d++ = c;
          }
        } else {
          if (c == '%' && !good) {
            *d++ = '%'; *d++ = '2'; *d++ = '5';
          } else if (c != '%' && enc_table[c]) {
            *d++ = '%'; *d++ = HEX[c >> 4]; *d++ = HEX[c & 0x0F];
          } else {
            *d++ = c;
          }
        }
      }
    }
  };
  lp_run(n, threads, work);
}

// Device-emitted Arrow views -> host view structs: the TPU executor
// appends, per span field, 4 int32 rows to its packed output — a merged
// span word (start | len<<13 | live<<26) and the span's first 12 bytes
// LE-packed into 3 words (masked beyond len).  This pass interleaves
// them into [F, B, 16] Arrow string_view structs with streaming stores —
// the host never touches the [B, L] byte buffer (the whole-buffer
// prefix gather was the single biggest memory-traffic term of the old
// host-side builder on a ~6.7 GB/s single-core host).
void lp_views_interleave(const int32_t* packed, int64_t stride,
                         const int64_t* field_rows, int64_t F,
                         int64_t B, int64_t L,
                         uint8_t* out, int32_t threads) {
  if (threads < 1) threads = 1;
  auto work = [&](int64_t flo, int64_t fhi) {
    for (int64_t f = flo; f < fhi; ++f) {
      const int32_t* m = packed + field_rows[f] * stride;
      const int32_t* p0 = m + stride;
      const int32_t* p1 = p0 + stride;
      const int32_t* p2 = p1 + stride;
      uint8_t* o = out + f * B * 16;
      for (int64_t r = 0; r < B; ++r) {
        int32_t w = m[r];
        int32_t v0 = 0, v1 = 0, v2 = 0, v3 = 0;
        if (w >> 26) {
          int32_t len = (w >> 13) & 0x1FFF;
          v0 = len;
          v1 = p0[r];
          if (len <= 12) {
            v2 = p1[r];
            v3 = p2[r];
          } else {
            v2 = 0;  // buffer index: the batch buffer
            v3 = static_cast<int32_t>(r * L) + (w & 0x1FFF);
          }
        }
#if defined(__SSE2__)
        // All stores share out's alignment (offsets are 16-multiples);
        // numpy buffers are 16-aligned in practice, but stay safe.
        __m128i v = _mm_set_epi32(v3, v2, v1, v0);
        __m128i* dst = reinterpret_cast<__m128i*>(o + r * 16);
        if ((reinterpret_cast<uintptr_t>(out) & 15) == 0) {
          _mm_stream_si128(dst, v);  // write-only output: skip the RFO
        } else {
          _mm_storeu_si128(dst, v);
        }
#else
        int32_t* vi = reinterpret_cast<int32_t*>(o + r * 16);
        vi[0] = v0; vi[1] = v1; vi[2] = v2; vi[3] = v3;
#endif
      }
    }
  };
  // weight=B: F is a handful of fields, each B rows of work — without it
  // the small-n cutoff would pin the pass to one thread on any host.
  lp_run(F, threads, work, B);
#if defined(__SSE2__)
  _mm_sfence();
#endif
}

// Fused special-row assembler for the Arrow view materializer: URI-repair
// (`fix`) and ?->& (`amp`) rows in ONE scan+write pair straight from the
// [B, L] batch buffer into the side buffer + patched view structs.
// NOTE: the per-byte repair classification below is a TWIN of
// lp_repair_scan/lp_repair_write (different source addressing + the i==0
// amp substitution).  Any semantics change must be applied to BOTH pairs
// and to arrow_bridge._repair_fix_segments — tests/test_fuzz_differential
// locks all three against the oracle and fails on divergence.  The
// Python flow this replaces (gather segments -> repair -> scatter clean +
// repaired -> patch views) spent more time in numpy indexing and per-call
// dispatch than in byte work (~1.2 ms/column at 16k rows for ~0.6 MB of
// bytes).  Per special row j at rows[j]:
//   - amp_flags[j]: the span's first byte reads '&' (query normalization)
//     before any repair sees it;
//   - fix_flags[j]: lp_repair_scan/write semantics apply (mode/enc_table);
//     rows needing exact Python UTF-8 semantics set py_flags[j] and write
//     nothing (out_lens[j] = 0; the caller patches them from its own side
//     buffer);
//   - otherwise the span bytes copy verbatim.
// lp_special_write also patches views[rows[j]] with the
// inline-vs-reference encoding (buffer_index for long values).
void lp_special_scan(const uint8_t* buf, int64_t L, const int32_t* starts,
                     const int64_t* rows, const int64_t* span_lens,
                     const uint8_t* fix_flags, const uint8_t* amp_flags,
                     int64_t n, int32_t mode, const uint8_t* enc_table,
                     int64_t* out_lens, uint8_t* py_flags, int32_t threads) {
  if (threads < 1) threads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t j = lo; j < hi; ++j) {
#if defined(__GNUC__)
      // The span reads jump row-to-row through the [B, L] buffer —
      // without prefetch each fix row pays a cold DRAM miss (the pass
      // runs right after a fetch; nothing else streams the buffer).
      if (j + 8 < hi) {
        __builtin_prefetch(buf + rows[j + 8] * L + starts[rows[j + 8]]);
      }
#endif
      int64_t len = span_lens[j];
      if (!fix_flags[j]) {
        py_flags[j] = 0;
        out_lens[j] = len;
        continue;
      }
      const uint8_t* s = buf + rows[j] * L + starts[rows[j]];
      bool amp = amp_flags[j] != 0;
      bool py = false;
      int64_t out = len;
      for (int64_t i = 0; i < len; ++i) {
        uint8_t c = (i == 0 && amp) ? static_cast<uint8_t>('&') : s[i];
        if (c >= 0x80) { py = true; break; }
        if (c == '%' && i + 2 < len && lp_is_hex(s[i + 1]) &&
            lp_is_hex(s[i + 2])) {
          if (mode == 0) {
            int dec = (lp_hex_val(s[i + 1]) << 4) | lp_hex_val(s[i + 2]);
            if (dec >= 0x80) { py = true; break; }
            out -= 2;
            i += 2;
          }
        } else if (mode == 1 && (c == '%' || enc_table[c])) {
          out += 2;
        }
      }
      py_flags[j] = py ? 1 : 0;
      out_lens[j] = py ? 0 : out;
    }
  };
  lp_run(n, threads, work);
}

void lp_special_write(const uint8_t* buf, int64_t L, const int32_t* starts,
                      const int64_t* rows, const int64_t* span_lens,
                      const uint8_t* fix_flags, const uint8_t* amp_flags,
                      int64_t n, int32_t mode, const uint8_t* enc_table,
                      const int64_t* side_off, const uint8_t* py_flags,
                      uint8_t* side, uint8_t* views, int32_t buffer_index,
                      int32_t threads) {
  static const char HEX[] = "0123456789ABCDEF";
  if (threads < 1) threads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t j = lo; j < hi; ++j) {
#if defined(__GNUC__)
      if (j + 8 < hi) {
        const uint8_t* p = buf + rows[j + 8] * L + starts[rows[j + 8]];
        __builtin_prefetch(p);
        __builtin_prefetch(p + 64);
      }
#endif
      if (py_flags[j]) continue;  // caller patches these rows itself
      const uint8_t* s = buf + rows[j] * L + starts[rows[j]];
      int64_t len = span_lens[j];
      int64_t off = side_off[j];
      uint8_t* d = side + off;
      bool amp = amp_flags[j] != 0;
      if (!fix_flags[j]) {
        if (len > 0) {
          std::memcpy(d, s, static_cast<size_t>(len));
          if (amp) d[0] = '&';
        }
      } else {
        for (int64_t i = 0; i < len; ++i) {
          uint8_t c = (i == 0 && amp) ? static_cast<uint8_t>('&') : s[i];
          bool good = c == '%' && i + 2 < len && lp_is_hex(s[i + 1]) &&
                      lp_is_hex(s[i + 2]);
          if (mode == 0) {
            if (good) {
              *d++ = static_cast<uint8_t>(
                  (lp_hex_val(s[i + 1]) << 4) | lp_hex_val(s[i + 2]));
              i += 2;
            } else {
              *d++ = c;
            }
          } else {
            if (c == '%' && !good) {
              *d++ = '%'; *d++ = '2'; *d++ = '5';
            } else if (c != '%' && enc_table[c]) {
              *d++ = '%'; *d++ = HEX[c >> 4]; *d++ = HEX[c & 0x0F];
            } else {
              *d++ = c;
            }
          }
        }
      }
      lp_encode_view(views + rows[j] * 16, side + off,
                     static_cast<int32_t>(side_off[j + 1] - off),
                     buffer_index, off);
    }
  };
  lp_run(n, threads, work);
}

// One-shot convenience: frame + pack a whole blob.  Returns line count.
int64_t lp_frame_pack(const uint8_t* data, int64_t size,
                      uint8_t* out, int32_t* lengths,
                      int64_t max_lines, int64_t L, int32_t threads) {
  std::vector<int64_t> offsets(max_lines);
  std::vector<int32_t> lens(max_lines);
  int64_t n = lp_frame(data, size, offsets.data(), lens.data(), max_lines);
  lp_pack(data, offsets.data(), lens.data(), n, out, lengths, L, threads);
  return n;
}

}  // extern "C"
