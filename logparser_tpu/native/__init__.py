"""Native host tier: C++ line framing/packing with a pure-numpy fallback.

``encode_blob(data)`` is the product ingest path: newline-delimited log bytes
-> (padded [B, L] uint8 buffer, lengths, overflow rows) ready for the device
pipeline.  The C++ library (logframe.cc) is compiled on first use with the
baked-in g++ toolchain and bound via ctypes (no pybind11 in the image); when
no compiler is available the numpy fallback keeps everything working at
reduced host throughput.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "logframe.cc")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_OVERFLOW_BIT = 1 << 30

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _compile_lib() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_BUILD_DIR, f"logframe-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", tmp]
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
    except (OSError, subprocess.SubprocessError):
        # No toolchain or a read-only install tree: numpy fallback.
        return None
    return so_path


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, compiling it on first use; None if the
    toolchain is unavailable (callers fall back to numpy)."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        so_path = _compile_lib()
        if so_path is None:
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(so_path)
        except OSError:
            # A prebuilt .so for another platform (e.g. a linux library
            # inside a wheel installed on macOS): numpy fallback, never
            # a crash.
            _lib_failed = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.lp_scan.argtypes = [u8p, ctypes.c_int64, i64p, i64p]
        lib.lp_scan.restype = None
        lib.lp_frame.argtypes = [u8p, ctypes.c_int64, i64p, i32p, ctypes.c_int64]
        lib.lp_frame.restype = ctypes.c_int64
        lib.lp_pack.argtypes = [u8p, i64p, i32p, ctypes.c_int64, u8p, i32p,
                                ctypes.c_int64, ctypes.c_int32]
        lib.lp_pack.restype = None
        lib.lp_frame_pack.argtypes = [u8p, ctypes.c_int64, u8p, i32p,
                                      ctypes.c_int64, ctypes.c_int64,
                                      ctypes.c_int32]
        lib.lp_frame_pack.restype = ctypes.c_int64
        lib.lp_gather_spans.argtypes = [u8p, ctypes.c_int64, ctypes.c_int64,
                                        i32p, i64p, u8p, ctypes.c_int32]
        lib.lp_gather_spans.restype = None
        lib.lp_gather_spans_multi.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int64, i32p, i64p, u8p,
            ctypes.c_int64, ctypes.c_int32,
        ]
        lib.lp_gather_spans_multi.restype = None
        lib.lp_copy_spans.argtypes = [u8p, i64p, u8p, i64p,
                                      ctypes.c_int64, ctypes.c_int32]
        lib.lp_copy_spans.restype = None
        if hasattr(lib, "lp_scatter_spans"):
            lib.lp_scatter_spans.argtypes = [
                u8p, i64p, i64p, u8p, i64p, ctypes.c_int64, ctypes.c_int32,
            ]
            lib.lp_scatter_spans.restype = None
        if hasattr(lib, "lp_build_views"):
            # Older cached .so builds predate the view materializer.
            lib.lp_build_views.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int64, i32p, i32p, u8p,
                ctypes.c_int64, ctypes.c_int32,
            ]
            lib.lp_build_views.restype = None
        if hasattr(lib, "lp_patch_views"):
            lib.lp_patch_views.argtypes = [
                u8p, i64p, i64p, ctypes.c_int64, ctypes.c_int32, u8p,
            ]
            lib.lp_patch_views.restype = None
        if hasattr(lib, "lp_views_interleave"):
            lib.lp_views_interleave.argtypes = [
                i32p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, u8p, ctypes.c_int32,
            ]
            lib.lp_views_interleave.restype = None
        if hasattr(lib, "lp_special_scan"):
            lib.lp_special_scan.argtypes = [
                u8p, ctypes.c_int64, i32p, i64p, i64p, u8p, u8p,
                ctypes.c_int64, ctypes.c_int32, u8p, i64p, u8p,
                ctypes.c_int32,
            ]
            lib.lp_special_scan.restype = None
            lib.lp_special_write.argtypes = [
                u8p, ctypes.c_int64, i32p, i64p, i64p, u8p, u8p,
                ctypes.c_int64, ctypes.c_int32, u8p, i64p, u8p, u8p, u8p,
                ctypes.c_int32, ctypes.c_int32,
            ]
            lib.lp_special_write.restype = None
        if hasattr(lib, "lp_repair_scan"):
            lib.lp_repair_scan.argtypes = [
                u8p, i64p, ctypes.c_int64, ctypes.c_int32, u8p, i64p, u8p,
                ctypes.c_int32,
            ]
            lib.lp_repair_scan.restype = None
            lib.lp_repair_write.argtypes = [
                u8p, i64p, ctypes.c_int64, ctypes.c_int32, u8p, i64p, u8p,
                u8p, ctypes.c_int32,
            ]
            lib.lp_repair_write.restype = None
        _lib = lib
        return _lib


def build_tool(source_path: str, stem: str) -> Optional[str]:
    """Compile one standalone C++ TOOL (an executable, not a ctypes
    library) with the same baked-in toolchain `_compile_lib` uses, cached
    in ``_build/`` by source digest.  Returns the binary path, or None
    when no toolchain is available (callers fall back / skip — exactly
    the logframe.cc contract).  Used by the protocol reference client
    (``svc_client.cc``, docs/PROTOCOL.md) and available to future
    tools."""
    try:
        with open(source_path, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    exe_path = os.path.join(_BUILD_DIR, f"{stem}-{digest}")
    if os.path.exists(exe_path):
        return exe_path
    tmp = exe_path + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-pthread", source_path, "-o", tmp]
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, exe_path)
    except (OSError, subprocess.SubprocessError):
        return None
    return exe_path


def svc_client_path() -> Optional[str]:
    """The compiled protocol reference client (svc_client.cc); None when
    the toolchain is unavailable."""
    return build_tool(
        os.path.join(os.path.dirname(__file__), "svc_client.cc"),
        "svc_client",
    )


def native_available() -> bool:
    return get_lib() is not None


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


_DEFAULT_THREADS = min(8, os.cpu_count() or 1)


def _default_threads() -> int:
    return _DEFAULT_THREADS


def _bucket(max_len: int, min_bucket: int, cap: int) -> int:
    """The single bucket-length implementation (tpu.runtime.bucket_length
    delegates here; this module stays jax-free).  Hybrid scheme balancing
    padding waste against jit-recompile churn — each distinct L compiles its
    own executor, so the bucket count must stay small:
    power of two up to 128, multiples of 128 (the TPU lane width) up to 512,
    multiples of 256 up to 1024, then powers of two up to cap.
    That is ~8 shapes total instead of 32 for pure 128-multiples, while the
    common access-log range (129..512 bytes) still pads to at most 127
    wasted bytes per line."""
    if max_len <= min_bucket:
        return min_bucket
    if max_len <= 128:
        return 128 if min_bucket < 128 else min_bucket
    if max_len <= 512:
        size = -(-max_len // 128) * 128
    elif max_len <= 1024:
        size = -(-max_len // 256) * 256
    else:
        size = 2048
        while size < max_len:
            size *= 2
    return min(size, cap)


def encode_blob(
    data: bytes,
    line_len: int = 0,
    min_bucket: int = 64,
    cap: int = 8191,  # tpu.runtime.DEFAULT_MAX_LINE_LEN (13-bit span slots)
    threads: int = 0,
    alloc=None,
) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """Newline-delimited bytes -> (buf [B, L] uint8, lengths [B] int32,
    overflow row indices).  L is the length bucket of the longest line
    (<= cap) unless ``line_len`` pins it.

    ``alloc(n, L) -> (buf [n, L] uint8, lengths [n] int32)`` supplies the
    destination arrays (e.g. shared-memory slot views: the feeder ring
    frames batches directly into the transport arena, no staging copy).
    The packed result is byte-identical to the self-allocating path even
    when the destination is a recycled slot: ``lp_pack`` writes EVERY
    byte of rows [0, n) (line bytes + padding memset), so no pre-zeroing
    is needed on the native path — only the empty-blob placeholder row
    is cleared explicitly.  ``alloc`` may raise to reject the (n, L)
    shape (slot capacity); the exception propagates to the caller."""
    blob = np.frombuffer(data, dtype=np.uint8)
    lib = get_lib()
    if lib is None:
        return _encode_blob_numpy(data, line_len, min_bucket, cap, alloc)

    n_lines = ctypes.c_int64()
    max_len = ctypes.c_int64()
    lib.lp_scan(_u8(blob), blob.size, ctypes.byref(n_lines),
                ctypes.byref(max_len))
    n = n_lines.value
    if line_len <= 0:
        L = _bucket(max_len.value, min_bucket, cap)
    else:
        L = line_len
    if alloc is not None:
        buf, lengths = alloc(max(n, 1), L)
        if n == 0:  # placeholder row lp_pack never touches
            buf[:] = 0
            lengths[:] = 0
    else:
        buf = np.zeros((max(n, 1), L), dtype=np.uint8)
        lengths = np.zeros(max(n, 1), dtype=np.int32)
    if n:
        lib.lp_frame_pack(
            _u8(blob), blob.size, _u8(buf),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, L, threads or _default_threads(),
        )
    overflow = np.nonzero(lengths & _OVERFLOW_BIT)[0]
    if alloc is not None:
        # Caller-provided destination (slot view): strip the overflow
        # bit IN PLACE so the transported lengths are the clean ones.
        lengths &= ~_OVERFLOW_BIT
    else:
        lengths = (lengths & ~_OVERFLOW_BIT).astype(np.int32)
    return buf[:n], lengths[:n], [int(i) for i in overflow if i < n]


def gather_spans(
    buf: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    threads: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Materialize per-row spans of a [B, L] buffer as one flat byte array.

    Returns (data, offsets64): row r's bytes are
    ``data[offsets[r]:offsets[r+1]]``.  Rows with lens[r] == 0 are empty.
    The C++ path runs a threaded memcpy fan-out; the numpy fallback uses
    the repeat-index gather (same algorithm as the Arrow bridge).
    """
    B, L = buf.shape
    lens64 = np.asarray(lens, dtype=np.int64)
    offsets = np.zeros(B + 1, dtype=np.int64)
    np.cumsum(lens64, out=offsets[1:])
    total = int(offsets[-1])
    lib = get_lib()
    starts32 = np.ascontiguousarray(starts, dtype=np.int32)
    buf_c = np.ascontiguousarray(buf)
    if lib is not None:
        data = np.empty(total, dtype=np.uint8)
        lib.lp_gather_spans(
            _u8(buf_c), B, L,
            starts32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _u8(data), threads or _default_threads(),
        )
        return data, offsets
    row_base = np.arange(B, dtype=np.int64) * L + starts32
    idx = np.repeat(row_base - offsets[:-1], lens64) + np.arange(
        total, dtype=np.int64
    )
    return buf_c.reshape(-1)[idx], offsets


def gather_spans_multi(
    buf: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    threads: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Gather K span columns of the same [B, L] buffer in ONE call.

    ``starts`` and ``lens`` are [K, B]; returns (data, offsets64[K*B+1])
    where column k's offsets are ``offsets[k*B : k*B+B+1]`` (subtract
    ``offsets[k*B]`` for column-local offsets) and its bytes are the
    matching contiguous slice of ``data``.  One threaded fan-out covers
    all columns — the per-call pool-spawn cost that dominates per-column
    gathers at typical batch sizes is paid once per batch instead.
    """
    K, B = starts.shape
    L = buf.shape[1]
    lens64 = np.asarray(lens, dtype=np.int64).reshape(-1)
    offsets = np.zeros(K * B + 1, dtype=np.int64)
    np.cumsum(lens64, out=offsets[1:])
    total = int(offsets[-1])
    starts32 = np.ascontiguousarray(starts, dtype=np.int32).reshape(-1)
    buf_c = np.ascontiguousarray(buf)
    lib = get_lib()
    if lib is not None:
        data = np.empty(total, dtype=np.uint8)
        lib.lp_gather_spans_multi(
            _u8(buf_c), B, L,
            starts32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _u8(data), K, threads or _default_threads(),
        )
        return data, offsets
    row_base = np.tile(np.arange(B, dtype=np.int64) * L, K) + starts32
    idx = np.repeat(row_base - offsets[:-1], lens64) + np.arange(
        total, dtype=np.int64
    )
    return buf_c.reshape(-1)[idx], offsets


def copy_spans(
    src: np.ndarray,
    src_off: np.ndarray,
    dst_off: np.ndarray,
    threads: int = 0,
) -> np.ndarray:
    """Per-row flat re-layout: returns ``out`` with
    ``out[dst_off[r]:dst_off[r+1]] == src[src_off[r]:src_off[r]+len_r]``
    (lengths from the dst offsets).  C++ threaded memcpy fan-out; numpy
    repeat-gather fallback."""
    if src.dtype != np.uint8:
        raise TypeError(f"copy_spans needs uint8 src, got {src.dtype}")
    n = len(dst_off) - 1
    total = int(dst_off[-1])
    src_off64 = np.ascontiguousarray(src_off, dtype=np.int64)
    dst_off64 = np.ascontiguousarray(dst_off, dtype=np.int64)
    src_c = np.ascontiguousarray(src)
    lib = get_lib()
    if lib is not None:
        out = np.empty(total, dtype=np.uint8)
        lib.lp_copy_spans(
            _u8(src_c),
            src_off64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _u8(out),
            dst_off64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, threads or _default_threads(),
        )
        return out
    lens = np.diff(dst_off64)
    idx = np.repeat(src_off64 - dst_off64[:-1], lens) + np.arange(
        total, dtype=np.int64
    )
    return src_c[idx]


def scatter_spans(
    src: np.ndarray,
    src_off: np.ndarray,
    lens: np.ndarray,
    out: np.ndarray,
    dst_off: np.ndarray,
    threads: int = 0,
) -> None:
    """Scatter per-row spans into a caller-provided flat buffer:
    ``out[dst_off[r]:dst_off[r]+lens[r]] = src[src_off[r]:...]``.
    Unlike :func:`copy_spans`, lengths are explicit and ``dst_off`` need
    not be contiguous — row subsets interleave into one shared side
    buffer.  C++ threaded memcpy fan-out; numpy repeat-gather fallback."""
    if src.dtype != np.uint8 or out.dtype != np.uint8:
        raise TypeError("scatter_spans needs uint8 src/out")
    n = len(lens)
    if n == 0:
        return
    src_off64 = np.ascontiguousarray(src_off, dtype=np.int64)
    dst_off64 = np.ascontiguousarray(dst_off, dtype=np.int64)
    lens64 = np.ascontiguousarray(lens, dtype=np.int64)
    src_c = np.ascontiguousarray(src)
    lib = get_lib()
    if lib is not None and hasattr(lib, "lp_scatter_spans"):
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.lp_scatter_spans(
            _u8(src_c), src_off64.ctypes.data_as(i64p),
            lens64.ctypes.data_as(i64p), _u8(out),
            dst_off64.ctypes.data_as(i64p),
            n, threads or _default_threads(),
        )
        return
    live = lens64 > 0
    if not live.any():
        return
    sl = lens64[live]
    src_idx = np.repeat(src_off64[live], sl) + _ramp(sl)
    dst_idx = np.repeat(dst_off64[live], sl) + _ramp(sl)
    out[dst_idx] = src_c[src_idx]


def _ramp(lens: np.ndarray) -> np.ndarray:
    """[0..l0-1, 0..l1-1, ...] for positive lens."""
    total = int(lens.sum())
    ends = np.cumsum(lens)
    return np.arange(total, dtype=np.int64) - np.repeat(
        ends - lens, lens
    )


def build_views(
    buf: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    threads: int = 0,
) -> np.ndarray:
    """Arrow BinaryView structs for K span columns of a [B, L] buffer.

    ``starts``/``lens`` are [K, B] (lens < 0 = null row -> zeroed view).
    Returns a [K, B, 16] uint8 array of Arrow string_view structs whose
    long strings reference the FLATTENED buffer at offset ``r*L + start``
    (buffer index 0) — no byte gather at all; strings of <= 12 bytes are
    inlined per the Arrow spec.  Caller guarantees B*L < 2^31."""
    starts2 = np.ascontiguousarray(starts, dtype=np.int32)
    K, B = starts2.shape
    L = buf.shape[1]
    if B * L >= 2**31:
        raise ValueError("buffer too large for int32 view offsets")
    lens2 = np.ascontiguousarray(lens, dtype=np.int32)
    buf_c = np.ascontiguousarray(buf)
    views = _pooled_empty_u8(K * B * 16)
    lib = get_lib()
    if lib is not None and hasattr(lib, "lp_build_views"):
        lib.lp_build_views(
            _u8(buf_c), B, L,
            starts2.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lens2.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            _u8(views), K, threads or _default_threads(),
        )
        return views.reshape(K, B, 16)
    # numpy fallback: same encoding, vectorized.
    views = views.reshape(K * B, 16)
    views[:] = 0
    flat = buf_c.reshape(-1)
    sf = starts2.reshape(-1).astype(np.int64)
    lf = lens2.reshape(-1).astype(np.int64)
    live = lf >= 0
    ln = np.where(live, lf, 0)
    vi32 = views.view(np.int32).reshape(K * B, 4)
    vi32[live, 0] = ln[live].astype(np.int32)
    abs_off = np.tile(np.arange(B, dtype=np.int64) * L, K) + sf
    idx = np.minimum(abs_off[:, None] + np.arange(12), B * L - 1)
    first12 = flat[idx]
    mask = np.arange(12)[None, :] < np.minimum(ln, 12)[:, None]
    views[:, 4:16] = np.where(mask & live[:, None], first12, 0)
    long_rows = live & (lf > 12)
    vi32[long_rows, 2] = 0
    vi32[long_rows, 3] = abs_off[long_rows].astype(np.int32)
    return views.reshape(K, B, 16)


def patch_views(
    views: np.ndarray,
    rows: np.ndarray,
    side: np.ndarray,
    side_off: np.ndarray,
    buffer_index: int,
) -> None:
    """Re-point selected rows of a [B, 16] view array at a side buffer
    (repaired/overridden values).  ``side_off`` is [n_rows+1] into
    ``side``; C++ row loop with a vectorized numpy fallback."""
    n = rows.size
    if n == 0:
        return
    lib = get_lib()
    if lib is not None and hasattr(lib, "lp_patch_views"):
        rows64 = np.ascontiguousarray(rows, dtype=np.int64)
        side_c = np.ascontiguousarray(side)
        off64 = np.ascontiguousarray(side_off, dtype=np.int64)
        lib.lp_patch_views(
            _u8(side_c if len(side_c) else np.zeros(1, np.uint8)),
            off64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            rows64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, buffer_index, _u8(views),
        )
        return
    lens = np.diff(side_off).astype(np.int64)
    sub = np.zeros((n, 16), dtype=np.uint8)
    v32 = sub.view(np.int32).reshape(n, 4)
    v32[:, 0] = lens.astype(np.int32)
    idx = np.minimum(side_off[:-1, None] + np.arange(12),
                     max(len(side) - 1, 0))
    first12 = side[idx] if len(side) else np.zeros((n, 12), np.uint8)
    mask = np.arange(12)[None, :] < np.minimum(lens, 12)[:, None]
    sub[:, 4:16] = np.where(mask, first12, 0)
    long_rows = lens > 12
    v32[long_rows, 2] = buffer_index
    v32[long_rows, 3] = side_off[:-1][long_rows].astype(np.int32)
    views[rows] = sub


def repair_spans(seg: np.ndarray, seg_off: np.ndarray, escape_mode: bool,
                 enc_table: np.ndarray, threads: int = 0):
    """Native URI-repair of per-row segments: returns
    (out_flat, out_lens int64[n], py_flags bool[n]) where py-flagged rows
    (non-ASCII / non-ASCII decode) are zero-length in out_flat and must be
    repaired per-row in Python.  None when the native library (or the
    repair entry points) is unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lp_repair_scan"):
        return None
    n = len(seg_off) - 1
    seg_c = np.ascontiguousarray(seg)
    off64 = np.ascontiguousarray(seg_off, dtype=np.int64)
    enc_c = np.ascontiguousarray(enc_table, dtype=np.uint8)
    out_lens = np.empty(n, dtype=np.int64)
    py_flags = np.empty(n, dtype=np.uint8)
    mode = 1 if escape_mode else 0
    nthreads = threads or _default_threads()
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.lp_repair_scan(
        _u8(seg_c if len(seg_c) else np.zeros(1, np.uint8)),
        off64.ctypes.data_as(i64p), n, mode, _u8(enc_c),
        out_lens.ctypes.data_as(i64p), _u8(py_flags), nthreads,
    )
    out_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(out_lens, out=out_off[1:])
    out = np.empty(int(out_off[-1]), dtype=np.uint8)
    lib.lp_repair_write(
        _u8(seg_c if len(seg_c) else np.zeros(1, np.uint8)),
        off64.ctypes.data_as(i64p), n, mode, _u8(enc_c),
        out_off.ctypes.data_as(i64p), _u8(py_flags),
        _u8(out if len(out) else np.zeros(1, np.uint8)), nthreads,
    )
    return out, out_lens, py_flags.astype(bool)


# Output-buffer pool for the fixed-size per-batch view arrays: a fresh
# np.empty of ~2 MB pays ~0.2 ms of page faults per call on this host
# (the kernel itself runs in ~0.18 ms).  An entry is reused only when
# nothing else holds it — Arrow buffers built on a pooled array keep a
# reference, so a table still alive blocks reuse (refcount check).
# The exact-refcount test assumes GIL-serialized refcounting: on a
# free-threaded build (PEP 703, deferred/biased counts) the pool is
# disabled and every call allocates fresh.
_BUF_POOL: Dict[int, np.ndarray] = {}
_BUF_POOL_MAX = 16
_BUF_POOL_ENABLED = getattr(sys, "_is_gil_enabled", lambda: True)()


def _pooled_empty_u8(n: int) -> np.ndarray:
    if not _BUF_POOL_ENABLED:
        return np.empty(n, dtype=np.uint8)
    arr = _BUF_POOL.get(n)
    # 3 == dict entry + local binding + getrefcount argument: sole owner.
    if arr is not None and sys.getrefcount(arr) == 3:
        return arr
    if len(_BUF_POOL) >= _BUF_POOL_MAX:
        _BUF_POOL.clear()
    arr = np.empty(n, dtype=np.uint8)
    _BUF_POOL[n] = arr
    return arr


def views_interleave(
    packed: np.ndarray,
    field_rows: np.ndarray,
    B: int,
    L: int,
    threads: int = 0,
):
    """Device-emitted view rows -> [F, B, 16] Arrow string_view structs.

    ``packed`` is the fetched [K, stride] int32 device output;
    ``field_rows`` holds, per span field, the row index of its merged
    span word (rows +1..+3 carry the LE-packed first-12 bytes).  Returns
    None when the native library is unavailable (callers fall back to the
    host-side builder)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lp_views_interleave"):
        return None
    if packed.dtype != np.int32 or not packed.flags.c_contiguous:
        return None
    if B * L >= 2**31:
        # int32 view offsets (r*L + start) would wrap — same guard as
        # build_views (callers fall back to paths that raise loudly).
        return None
    F = field_rows.size
    rows64 = np.ascontiguousarray(field_rows, dtype=np.int64)
    out = _pooled_empty_u8(F * B * 16)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.lp_views_interleave(
        packed.ctypes.data_as(i32p), packed.shape[1],
        rows64.ctypes.data_as(i64p), F, B, L, _u8(out),
        threads or _default_threads(),
    )
    return out.reshape(F, B, 16)


def assemble_special(
    buf: np.ndarray,
    starts: np.ndarray,
    rows: np.ndarray,
    span_lens: np.ndarray,
    fix_flags: np.ndarray,
    amp_flags: np.ndarray,
    mode: int,
    enc_table: np.ndarray,
    views: np.ndarray,
    buffer_index: int,
    threads: int = 0,
):
    """Fused side-buffer build + view patch for the Arrow materializer's
    special rows (URI-repair ``fix`` + ``amp`` query normalization).

    ``buf`` is the [B, L] batch buffer, ``starts`` the column's [B] span
    starts, ``rows``/``span_lens``/``fix_flags``/``amp_flags`` the
    per-special-row data, ``views`` the [B, 16] view array patched in
    place.  Returns (side, side_off, py_flags) — py-flagged rows (exact
    Python UTF-8 semantics) are zero-length in ``side`` and NOT patched;
    the caller repairs and patches them itself.  None when the native
    library (or these entry points) is unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lp_special_scan"):
        return None
    n = rows.size
    L = buf.shape[1]
    buf_c = np.ascontiguousarray(buf)
    starts32 = np.ascontiguousarray(starts, dtype=np.int32)
    rows64 = np.ascontiguousarray(rows, dtype=np.int64)
    lens64 = np.ascontiguousarray(span_lens, dtype=np.int64)
    fix_u8 = np.ascontiguousarray(fix_flags, dtype=np.uint8)
    amp_u8 = np.ascontiguousarray(amp_flags, dtype=np.uint8)
    enc_c = np.ascontiguousarray(enc_table, dtype=np.uint8)
    out_lens = np.empty(n, dtype=np.int64)
    py_flags = np.empty(n, dtype=np.uint8)
    nthreads = threads or _default_threads()
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.lp_special_scan(
        _u8(buf_c), L, starts32.ctypes.data_as(i32p),
        rows64.ctypes.data_as(i64p), lens64.ctypes.data_as(i64p),
        _u8(fix_u8), _u8(amp_u8), n, mode, _u8(enc_c),
        out_lens.ctypes.data_as(i64p), _u8(py_flags), nthreads,
    )
    side_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(out_lens, out=side_off[1:])
    if int(side_off[-1]) >= 2**31:
        # lp_encode_view stores int32 offsets; a >2 GiB side buffer
        # (mode-1 repair can expand bytes 3x) would wrap them.  Callers
        # route the column to the copy path, which has its own guard.
        return "overflow"
    side = np.empty(int(side_off[-1]), dtype=np.uint8)
    lib.lp_special_write(
        _u8(buf_c), L, starts32.ctypes.data_as(i32p),
        rows64.ctypes.data_as(i64p), lens64.ctypes.data_as(i64p),
        _u8(fix_u8), _u8(amp_u8), n, mode, _u8(enc_c),
        side_off.ctypes.data_as(i64p), _u8(py_flags),
        _u8(side if len(side) else np.zeros(1, np.uint8)), _u8(views),
        buffer_index, nthreads,
    )
    return side, side_off, py_flags.astype(bool)


def _encode_blob_numpy(
    data: bytes, line_len: int, min_bucket: int, cap: int, alloc=None
) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """Pure-numpy fallback with identical semantics."""
    lines = bytes(data).split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    lines = [ln[:-1] if ln.endswith(b"\r") else ln for ln in lines]
    max_len = max((len(r) for r in lines), default=1)
    if line_len <= 0:
        L = _bucket(max_len, min_bucket, cap)
    else:
        L = line_len
    if alloc is not None:
        buf, lengths = alloc(max(len(lines), 1), L)
        buf[:] = 0
        lengths[:] = 0
    else:
        buf = np.zeros((max(len(lines), 1), L), dtype=np.uint8)
        lengths = np.zeros(max(len(lines), 1), dtype=np.int32)
    overflow: List[int] = []
    for i, r in enumerate(lines):
        if len(r) > L:
            overflow.append(i)
            r = r[:L]
        buf[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
        lengths[i] = len(r)
    return buf[: len(lines)], lengths[: len(lines)], overflow
