"""CI coalesce smoke: continuous batching against a LIVE sidecar
(docs/SERVICE.md "Continuous batching" acceptance drill).

Boots two in-process :class:`~logparser_tpu.service.ParseService`
instances — coalescing ON (generous window, so concurrent rounds land in
shared batches) and OFF (the solo reference) — and asserts:

1. **Byte parity** — K concurrent raw-socket sessions pushing
   interleaved mixed-size LINES frames through the coalescer receive
   ARROW payloads BYTE-identical to the same frames parsed solo, with
   zero resets (every response a well-formed frame).
2. **Real coalescing** — at least one shared batch carried >1 session
   (``service_coalesced_sessions_per_batch``).
3. **Exposition** — /metrics exposes the coalesce metric families in a
   structurally valid exposition (`metrics_smoke.validate_exposition`).
4. **C++ reference client** (skipped without a toolchain, like the
   logframe fallback): ``native/svc_client.cc`` replays the golden
   protocol vector 01 and its received ARROW payloads are byte-identical
   to a Python raw-socket replay of the same bytes — the carried
   VERDICT item: the protocol doc + vectors suffice to implement a
   working client in another language.  Its drive mode then runs 3 live
   requests through the coalescing service.

Usage::

    make coalesce-smoke
    python -m logparser_tpu.tools.coalesce_smoke
"""
from __future__ import annotations

import json
import os
import socket
import struct
import sys
import threading
import urllib.request
from typing import Dict, List, Optional, Tuple


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_response(sock: socket.socket) -> Tuple[str, bytes]:
    header = _recv_exact(sock, 4)
    if header is None:
        return "reset", b""
    (n,) = struct.unpack(">I", header)
    if n == 0xFFFFFFFF:
        (m,) = struct.unpack(">I", _recv_exact(sock, 4) or b"\0\0\0\0")
        return "error", _recv_exact(sock, m) or b""
    return "arrow", _recv_exact(sock, n) or b""


def _session(host: str, port: int, config: bytes,
             payloads: List[bytes], barrier: Optional[threading.Barrier],
             out: Dict[int, List[Tuple[str, bytes]]], idx: int) -> None:
    sock = socket.create_connection((host, port))
    try:
        sock.settimeout(120)
        _send_frame(sock, config)
        got = []
        for payload in payloads:
            if barrier is not None:
                barrier.wait(timeout=60)
            _send_frame(sock, payload)
            got.append(_recv_response(sock))
        out[idx] = got
        sock.sendall(struct.pack(">I", 0))
    finally:
        sock.close()


def _replay_python(host: str, port: int, path: str) -> List[bytes]:
    with open(path, "rb") as f:
        blob = f.read()
    sock = socket.create_connection((host, port))
    try:
        sock.settimeout(60)
        sock.sendall(blob)
        payloads = []
        while True:
            kind, body = _recv_response(sock)
            if kind == "reset":
                return payloads
            if kind == "arrow":
                payloads.append(body)
    finally:
        sock.close()


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from logparser_tpu.observability import metrics
    from logparser_tpu.service import ParseService
    from logparser_tpu.tools.demolog import generate_combined_lines
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    problems: List[str] = []
    config = json.dumps({
        "log_format": "combined",
        "fields": ["IP:connection.client.host",
                   "STRING:request.status.last",
                   "BYTES:response.body.bytes"],
        "timestamp_format": None,
    }).encode()
    corpus = generate_combined_lines(240, seed=23)
    sizes_by_session = [(1, 41, 9), (23, 2, 57), (11, 64, 5), (3, 17, 30)]
    payload_sets: List[List[bytes]] = []
    cursor = 0
    for sizes in sizes_by_session:
        payloads = []
        for n in sizes:
            rows = [corpus[(cursor + j) % len(corpus)] for j in range(n)]
            blob = "\n".join(rows).encode()
            payloads.append(struct.pack(">I", n) + blob)
            cursor += n
        payload_sets.append(payloads)

    spb = metrics().histogram("service_coalesced_sessions_per_batch")
    count0, sum0 = spb.count, spb.sum

    # Solo reference (coalescing OFF), sequential sessions.
    refs: Dict[int, List[Tuple[str, bytes]]] = {}
    with ParseService(coalesce=False) as solo:
        for i, payloads in enumerate(payload_sets):
            _session(solo.host, solo.port, config, payloads, None, refs, i)

    # Concurrent sessions through the coalescer.
    out: Dict[int, List[Tuple[str, bytes]]] = {}
    with ParseService(coalesce=True, coalesce_window_ms=50.0,
                      metrics_port=0) as svc:
        barrier = threading.Barrier(len(payload_sets))
        threads = [
            threading.Thread(target=_session,
                             args=(svc.host, svc.port, config, payloads,
                                   barrier, out, i))
            for i, payloads in enumerate(payload_sets)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)

        # 3) exposition + family presence, while the service is live.
        url = f"http://{svc.host}:{svc.metrics_port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            text = resp.read().decode("utf-8")
        problems.extend(validate_exposition(text))
        for needle in (
            "logparser_tpu_service_coalesce_batch_occupancy",
            "logparser_tpu_service_coalesce_wait_seconds",
            "logparser_tpu_service_coalesced_sessions_per_batch",
            "logparser_tpu_service_coalesce_batches_total",
        ):
            if needle not in text:
                problems.append(f"required metric absent: {needle}")

        # 4) the C++ reference client, against the same live service.
        from logparser_tpu.native import svc_client_path

        exe = svc_client_path()
        if exe is None:
            print("coalesce-smoke: no C++ toolchain; native client leg "
                  "skipped (numpy-fallback hosts)")
        else:
            import subprocess
            import tempfile

            golden = os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))),
                "tests", "golden", "protocol", "01_session_request.bin",
            )
            ref_payloads = _replay_python(svc.host, svc.port, golden)
            with tempfile.TemporaryDirectory() as td:
                proc = subprocess.run(
                    [exe, "--host", svc.host, "--port", str(svc.port),
                     "--replay", golden, "--dump-prefix", td + "/v"],
                    capture_output=True, text=True, timeout=120,
                )
                if proc.returncode != 0:
                    problems.append(
                        f"C++ client replay failed: {proc.stderr.strip()}"
                    )
                else:
                    for i, ref in enumerate(ref_payloads):
                        try:
                            with open(f"{td}/v{i}.bin", "rb") as f:
                                got = f.read()
                        except OSError:
                            got = None
                        if got != ref:
                            problems.append(
                                f"C++ client ARROW payload {i} not "
                                "byte-identical to the Python replay"
                            )
                # Drive mode: 3 live requests through the coalescer.
                cf = os.path.join(td, "config.json")
                lf = os.path.join(td, "lines.txt")
                with open(cf, "wb") as f:
                    f.write(config)
                with open(lf, "w") as f:
                    f.write("\n".join(corpus[:16]))
                proc = subprocess.run(
                    [exe, "--host", svc.host, "--port", str(svc.port),
                     "--config", cf, "--lines", lf, "--repeat", "3"],
                    capture_output=True, text=True, timeout=120,
                )
                try:
                    rec = json.loads(proc.stdout)
                except ValueError:
                    rec = {}
                if rec.get("ok") != 3 or rec.get("resets"):
                    problems.append(
                        f"C++ client drive mode: {proc.stdout.strip()} "
                        f"{proc.stderr.strip()}"
                    )

    # 1) byte parity + zero resets.
    for i, ref in refs.items():
        got = out.get(i)
        if got is None:
            problems.append(f"session {i} never completed")
            continue
        for r, (kind, body) in enumerate(got):
            if kind != "arrow":
                problems.append(
                    f"session {i} round {r}: {kind} instead of ARROW"
                )
            elif body != ref[r][1]:
                problems.append(
                    f"session {i} round {r}: coalesced bytes differ "
                    "from solo parse"
                )

    # 2) real coalescing happened.
    spb = metrics().histogram("service_coalesced_sessions_per_batch")
    batches = spb.count - count0
    sessions = spb.sum - sum0
    if not batches or sessions <= batches:
        problems.append(
            f"no shared batch coalesced >1 session "
            f"({sessions:.0f} sessions over {batches} batches)"
        )

    if problems:
        print("coalesce-smoke: FAIL")
        for p in problems:
            print(" -", p)
        return 1
    print(
        "coalesce-smoke: OK — "
        f"{len(payload_sets)} concurrent sessions byte-identical to solo, "
        f"{sessions:.0f} sessions over {batches} shared batches, "
        "coalesce families live on /metrics"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
