"""CI analytics smoke: the pushdown exactness contract on live tiers.

Drills the on-device analytics pushdown (docs/ANALYTICS.md) end to end
and fails (exit 1) unless:

- a LIVE service session configured with an ``aggregate`` spec returns
  an aggregate frame EQUAL to a local host-oracle referee over the same
  lines (forced garbage + long-overflow fold rows included), while a
  row session on the same server keeps serving row frames;
- the pushdown accounting moved: ``analytics_batches_total{path=
  "device"}`` and ``analytics_d2h_bytes_saved_total`` (the D2H bytes
  the aggregate path did NOT ship vs the packed row payload) are
  positive, and the saved bytes dominate what the aggregate fetch
  actually shipped (the >= 10x shrinkage the bench gates);
- an aggregate JOB (the jobs CLI with ``--aggregate``), SIGKILLed (-9)
  mid-run from another process and resumed, merges BYTE-IDENTICAL
  aggregate output to a single-shot run — both the ``merged_hash`` over
  shard sidecars and the merged ``AggregateState`` wire bytes — with
  committed shards never re-parsed;
- no session thread, temp file, or shared-memory segment leaks, and the
  rendered Prometheus exposition stays structurally valid with the
  ``analytics_*`` families present.

Usage::

    make agg-smoke
    python -m logparser_tpu.tools.agg_smoke
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

N_LINES = 60000
GARBAGE_EVERY = 997          # ~60 rejected lines across the corpus
OVERFLOW_EVERY = 1499        # ~40 forced 20-digit fold rows
SHARD_BYTES = 64 << 10       # 20+ shards: a wide mid-run kill window
BATCH_LINES = 1024
KILL_POLL_S = 0.05
KILL_TIMEOUT_S = 300.0
SHM_DIR = "/dev/shm"

FMT = "%h %u %>s %b"
FIELDS = [
    "IP:connection.client.host",
    "STRING:request.status.last",
    "BYTES:response.body.bytes",
]
OPS = [
    {"op": "count"},
    {"op": "count_by", "field": "STRING:request.status.last"},
    {"op": "top_k", "field": "IP:connection.client.host", "k": 5},
    {"op": "sum", "field": "BYTES:response.body.bytes"},
]


def _corpus(path: str) -> None:
    with open(path, "w") as f:
        for i in range(N_LINES):
            if i % GARBAGE_EVERY == 7:
                f.write(f"?? broken line {i} !! ::\n")
            elif i % OVERFLOW_EVERY == 11:
                # > int64 byte counter: the device must FOLD this row to
                # the host row path, and the merged sum must carry it.
                f.write(f"10.9.8.7 u{i} 200 {'9' * 20}\n")
            else:
                f.write(f"10.0.{(i >> 8) % 256}.{i % 256} u{i} "
                        f"{200 + i % 7} {100 + i % 9000}\n")


def _ring_segments():
    from logparser_tpu.feeder import RING_NAME_PREFIX

    if not os.path.isdir(SHM_DIR):
        return None
    return sorted(
        f for f in os.listdir(SHM_DIR) if f.startswith(RING_NAME_PREFIX)
    )


def _committed(out_dir: str) -> int:
    from logparser_tpu.jobs.manifest import count_committed_shards

    return count_committed_shards(out_dir)


def _service_leg(failures) -> None:
    from logparser_tpu.analytics import AggregateState
    from logparser_tpu.analytics.spec import parse_aggregate_config
    from logparser_tpu.observability import counter_sum
    from logparser_tpu.service import (
        ParseService,
        ParseServiceClient,
        ParseServiceError,
    )
    from logparser_tpu.tools.demolog import generate_combined_lines
    from logparser_tpu.tpu.batch import TpuBatchParser

    agg_fields = [
        "IP:connection.client.host",
        "STRING:request.status.last",
        "BYTES:response.body.bytes",
        "TIME.EPOCH:request.receive.time.epoch",
    ]
    ops = OPS + [{"op": "time_bucket",
                  "field": "TIME.EPOCH:request.receive.time.epoch",
                  "width_s": 3600}]
    lines = generate_combined_lines(2000, seed=23, garbage_fraction=0.01)
    lines[42] = ('9.8.7.6 - - [01/Jan/2026:00:00:00 +0000] '
                 f'"GET /big HTTP/1.1" 200 {"9" * 20} "-" "ua"')

    spec = parse_aggregate_config(ops)
    referee_parser = TpuBatchParser("combined", agg_fields)
    try:
        referee = AggregateState(spec)
        referee.update_from_result(referee_parser.parse_batch(lines))
    finally:
        referee_parser.close()

    threads_before = {t.ident for t in threading.enumerate()}
    d2h_saved_before = counter_sum("analytics_d2h_bytes_saved_total")
    device_batches_before = counter_sum(
        'analytics_batches_total{path="device"}')
    with ParseService() as svc:
        with ParseServiceClient(
            svc.host, svc.port, "combined", agg_fields, aggregate=ops
        ) as client:
            state = client.parse(lines)
        if not isinstance(state, AggregateState):
            failures.append(
                f"service aggregate session returned {type(state)!r}, "
                "not an AggregateState"
            )
        elif state != referee:
            failures.append(
                "service aggregate != local host-oracle referee:\n"
                f"  service: {state.summary()}\n"
                f"  referee: {referee.summary()}"
            )
        else:
            print("agg-smoke: service aggregate == referee over "
                  f"{len(lines)} lines (garbage + overflow folds "
                  "included)")
        # a row session on the same server still serves row frames
        with ParseServiceClient(
            svc.host, svc.port, "combined", agg_fields[:1]
        ) as client:
            table = client.parse(lines[:25])
        if getattr(table, "num_rows", None) != 25:
            failures.append("row session alongside the aggregate one "
                            f"returned {table!r}")
        # a bad spec must relay a structured config error
        try:
            ParseServiceClient(
                svc.host, svc.port, "combined", agg_fields,
                aggregate=[{"op": "sum",
                            "field": "STRING:request.status.last"}],
            ).parse(["x"])
            failures.append("bad aggregate spec was accepted")
        except ParseServiceError:
            pass

    d2h_saved = counter_sum(
        "analytics_d2h_bytes_saved_total") - d2h_saved_before
    device_batches = counter_sum(
        'analytics_batches_total{path="device"}') - device_batches_before
    if device_batches < 1:
        failures.append("analytics_batches_total{path=device} never "
                        "moved across the aggregate session")
    if d2h_saved <= 0:
        failures.append("analytics_d2h_bytes_saved_total never moved — "
                        "the aggregate path shipped as much as the row "
                        "path")
    else:
        print(f"agg-smoke: D2H saved {d2h_saved / 1e6:.2f} MB across "
              f"{int(device_batches)} device-aggregated batch(es)")

    time.sleep(0.5)
    leaked = [
        t.name for t in threading.enumerate()
        if t.ident not in threads_before and t.is_alive()
    ]
    if leaked:
        failures.append(f"leaked service threads: {leaked}")


def _jobs_leg(failures) -> None:
    from logparser_tpu.jobs import (
        JobManifest,
        JobSpec,
        leaked_temp_files,
        merged_hash,
        merged_job_aggregate,
        run_job,
    )

    tmp = tempfile.mkdtemp(prefix="logparser-agg-smoke-")
    corpus = os.path.join(tmp, "corpus.log")
    _corpus(corpus)
    agg_json = json.dumps(OPS)

    def spec(out_name):
        return JobSpec([corpus], FMT, FIELDS,
                       os.path.join(tmp, out_name),
                       shard_bytes=SHARD_BYTES, batch_lines=BATCH_LINES,
                       aggregate=agg_json)

    t0 = time.perf_counter()
    ref = run_job(spec("single-shot"))
    ref_wall = time.perf_counter() - t0
    if not ref.complete:
        failures.append(f"single-shot aggregate job incomplete: "
                        f"{ref.as_dict()}")
    if not ref.rejects:
        failures.append("single-shot aggregate job saw no rejects "
                        "(corpus has garbage lines)")
    ref_dir = spec("single-shot").out_dir
    ref_hash = merged_hash(ref_dir, JobManifest.load(ref_dir))
    ref_agg = merged_job_aggregate(ref_dir)
    print(f"agg-smoke: single-shot {ref.shards_total} shards, "
          f"count={ref_agg.data[0]}, {ref.rejects} rejects, "
          f"{ref.payload_bytes / max(ref_wall, 1e-9) / 1e6:.1f} MB/s")

    # ---- kill drill: SIGKILL the aggregate CLI mid-run, resume -------
    kill_dir = spec("killed").out_dir
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else repo_root
    )
    argv = [sys.executable, "-m", "logparser_tpu.jobs", corpus,
            "--format", FMT, "--out", kill_dir,
            "--shard-bytes", str(SHARD_BYTES),
            "--batch-lines", str(BATCH_LINES),
            "--aggregate", agg_json]
    for f in FIELDS:
        argv += ["--field", f]
    proc = subprocess.Popen(argv, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + KILL_TIMEOUT_S
    while time.monotonic() < deadline:
        if _committed(kill_dir) >= 2 or proc.poll() is not None:
            break
        time.sleep(KILL_POLL_S)
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    else:
        print("agg-smoke: WARNING subprocess finished before the kill "
              "window (fast host) — resume still asserted below")
    committed_at_kill = _committed(kill_dir)
    print(f"agg-smoke: job stopped with {committed_at_kill} of "
          f"{ref.shards_total} shards committed")
    if committed_at_kill >= ref.shards_total and proc.returncode == -9:
        failures.append("kill drill never landed mid-run")
    time.sleep(2.0)

    resumed = run_job(spec("killed"))
    if not resumed.complete:
        failures.append(f"resume incomplete: {resumed.as_dict()}")
    if resumed.skipped != committed_at_kill:
        failures.append(
            f"resume re-parsed committed work: skipped "
            f"{resumed.skipped}, manifest had {committed_at_kill} at kill"
        )
    kill_hash = merged_hash(kill_dir, JobManifest.load(kill_dir))
    kill_agg = merged_job_aggregate(kill_dir)
    if kill_hash != ref_hash:
        failures.append(
            "kill-drill sidecar output is NOT byte-identical "
            f"({kill_hash[:16]} != {ref_hash[:16]})"
        )
    if kill_agg.to_ipc_bytes() != ref_agg.to_ipc_bytes():
        failures.append("kill-drill merged aggregate differs from the "
                        "single-shot run")
    elif kill_hash == ref_hash:
        print(f"agg-smoke: kill+resume aggregate byte-identical "
              f"({kill_hash[:16]}), skipped {resumed.skipped} committed "
              "shards")

    for out_name in ("single-shot", "killed"):
        debris = leaked_temp_files(spec(out_name).out_dir)
        if debris:
            failures.append(f"{out_name}: leaked temp files {debris}")


def main() -> int:
    from logparser_tpu.observability import metrics
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    failures: list = []
    segments_before = _ring_segments()

    _service_leg(failures)
    _jobs_leg(failures)

    segments_after = _ring_segments()
    if segments_before is not None and segments_after is not None:
        leaked = sorted(set(segments_after) - set(segments_before))
        if leaked:
            failures.append(f"leaked shared-memory segments: {leaked}")

    text = metrics().prometheus_text()
    for needle in ("logparser_tpu_analytics_batches_total",
                   "logparser_tpu_analytics_d2h_bytes_saved_total",
                   "logparser_tpu_analytics_partial_merge_seconds"):
        if needle not in text:
            failures.append(f"/metrics exposition missing: {needle}")
    failures.extend(validate_exposition(text))

    if failures:
        print("AGG SMOKE FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print("agg-smoke OK: live aggregate session == host-oracle referee, "
          "D2H savings recorded, SIGKILL/resume aggregate job "
          "byte-identical, no leaked threads/temp files/shm segments")
    return 0


if __name__ == "__main__":
    sys.exit(main())
