"""Fault-injection harness for the feeder fabric (docs/FEEDER.md
"Failure model & recovery").

The supervision layer (``feeder/supervisor.py``) only earns trust if the
failures it recovers from can be produced ON PURPOSE, deterministically,
in tests and CI.  This module defines the injection points the feeder
worker loop consults and the spec grammar that arms them:

    LOGPARSER_TPU_CHAOS="kill_worker:worker=1:after=3;delay_put:seconds=0.01"

A spec is ``;``-separated faults; each fault is a name followed by
``:key=value`` params.  Faults (params in brackets are optional):

- ``kill_worker:after=N[:worker=W][:mode=hard|soft][:sticky=1]`` — the
  worker dies after emitting N batches (default 0 = before the first).
  ``hard`` (default): a process worker ``os._exit``s mid-flight — no
  error relay, the consumer sees a silently dead producer; a thread
  worker returns without its DONE messages (threads cannot be killed).
  ``soft``: raise — the worker relays MSG_ERROR before dying.
- ``poison_shard:shard=S[:after=N][:mode=hard|soft]`` — die while
  processing global shard S (after N of its batches).  STICKY by
  default: respawned workers inherit it, so the shard keeps killing its
  workers until the supervisor quarantines it — the poison-shard
  scenario.
- ``corrupt_descriptor:index=N[:worker=W][:field=generation|slot]`` —
  scramble the Nth ring slot descriptor this worker sends (0-based);
  the consumer's map-time validation must catch it.
- ``slot_overflow[:worker=W][:after=N][:count=M]`` — force
  :class:`~logparser_tpu.feeder.ring.SlotOverflow` on M consecutive
  frames (default: every frame — the overflow STORM that demotes the
  worker off the ring).
- ``drop_done[:worker=W][:shard=S]`` — swallow the shard-done /
  worker-done control messages: the worker emits shard S's batches then
  returns silently (a protocol stall the consumer must detect via the
  dead producer, not hang on).
- ``delay_put:seconds=X[:worker=W]`` — sleep X before every queue put
  (slow/wedged worker; pairs with the supervisor's worker deadline).

I/O fault primitives (consumer-side: the durable job writer's
``docs/JOBS.md`` failure drills and ``chaos_smoke`` both arm them —
they never reach feeder workers):

- ``io_error[:op=write|fsync|rename][:shard=S][:count=M][:sticky=1]`` —
  raise ``OSError(EIO)`` from the matching writer operation.  Default
  op: every op; default ``count=1`` (one transient fault — the retry
  ladder must absorb it).
- ``enospc[:op=...][:shard=S][:count=M][:sticky=1]`` — same injection
  point raising ``OSError(ENOSPC)`` (disk full).

``shard=S`` pins an I/O fault to ONE shard's writes; combined with
``sticky=1`` it keeps firing through every retry — the shard must FAIL
(and stay uncommitted in the manifest) while the job completes its
other shards: the "sticky-per-shard" drill.

Front-tier fault primitives (round 15: armed by
``FrontTier(chaos=...)`` or the env var; they drive the sidecar-fleet
supervision in ``logparser_tpu/front.py`` and never reach feeder
workers or the job writer):

- ``kill_sidecar:index=N[:after=S]`` — hard-kill sidecar N right after
  its S-th routed session (default 0 = the first) lands on it: the
  crash-failover drill (in-flight sessions must get structured
  ``BUSY{"reason":"sidecar_failover"}`` frames, never resets).
- ``wedge_sidecar:index=N[:after=S][:seconds=X]`` — SIGSTOP sidecar N
  after its S-th routed session (SIGCONT after X seconds; default
  stays stopped): alive but silent, the shape the heartbeat deadline
  must catch and kill.
- ``flap_sidecar:index=N[:count=M]`` — kill sidecar N the moment it
  (re)reports ready, M times (default 3): the crash loop the circuit
  breaker must open around.

Device-tier fault primitives (armed by ``TpuBatchParser`` — from the
env var at construction or ``arm_device_chaos`` — and consulted once
per device execution; inert everywhere else, docs/FAULTS.md):

- ``oom_batch[:count=M][:min_lines=N][:after=K][:sticky=1]`` — raise
  an injected ``RESOURCE_EXHAUSTED`` (:class:`DeviceOomError`) from
  executions of >= N lines (default 0 = every execution).  With
  ``min_lines`` set, bisected halves below the threshold SUCCEED —
  the OOM-recovery drill; ``sticky=1`` keeps firing (the bucket-clamp
  drill).
- ``wedge_device[:count=M][:seconds=X][:after=K]`` — the execution
  sleeps X seconds (default 30) before fetching: with the parser's
  execution deadline armed, the batch expires and reroutes to the
  oracle.
- ``fail_compile[:count=M][:after=K]`` — raise an injected compile
  failure (:class:`DeviceCompileError`): the parser key must demote to
  the host oracle (warn-once + counter), never raise out of the parse.

``after=K`` arms a device fault only from the K+1-th device execution
on (0 = immediately; bisect retry chunks count as executions too — a
drill that must not land inside another fault's recovery aims past it).

Pod-tier fault primitive (armed by ``pod.run_pod`` in subprocess mode;
the cloud-TPU preemption notice drill, docs/JOBS.md):

- ``preempt_host:host=H[:after=N]`` — SIGTERM host H's jobs CLI once
  its per-host manifest holds N committed shards (default 1): the CLI
  must finish the current shard boundary and exit with the resumable
  preemption code; the relaunch resumes with zero re-parsed shards.

``worker=W`` restricts a worker fault to one worker id (default: all).
``sticky=1`` makes a fault survive respawns/retries (default only for
``poison_shard``); everything else fires ``count`` times (worker faults:
first incarnation only) — a recovered worker is healthy, which is what
lets byte-parity runs complete.

The spec travels EXPLICITLY through ``run_worker``'s args (the pool
parses the env var — or an object passed as ``FeederPool(chaos=...)`` —
at start time): forkserver children inherit the forkserver's
environment, not the pool's at spawn time, so an env-only channel would
silently disarm process-mode faults.  Everything here is jax-free and
picklable; with no spec armed the worker loop never imports this
module.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

#: The env var FeederPool consults at start (see module docstring).
CHAOS_ENV = "LOGPARSER_TPU_CHAOS"

_KNOWN = {
    "kill_worker", "poison_shard", "corrupt_descriptor",
    "slot_overflow", "drop_done", "delay_put",
    "io_error", "enospc",
    "kill_sidecar", "wedge_sidecar", "flap_sidecar",
    "oom_batch", "wedge_device", "fail_compile",
    "preempt_host",
}

#: Consumer-side fault kinds: armed by the durable-job writer, inert in
#: feeder workers (WorkerChaos hooks filter by kind and never match).
IO_FAULTS = {"io_error", "enospc"}

#: Front-tier fault kinds: armed by logparser_tpu/front.py's fleet
#: supervision, inert everywhere else.
FRONT_FAULTS = {"kill_sidecar", "wedge_sidecar", "flap_sidecar"}

#: Device-tier fault kinds: armed by TpuBatchParser's fault layer
#: (docs/FAULTS.md), inert in feeder workers / writer / front.
DEVICE_FAULTS = {"oom_batch", "wedge_device", "fail_compile"}

#: Pod-tier fault kinds: armed by pod.run_pod's subprocess mode (the
#: cloud-TPU preemption drill), inert everywhere else.
POD_FAULTS = {"preempt_host"}


class _ChaosHardExit(BaseException):
    """Thread-worker 'hard' death: unwind run_worker WITHOUT the error
    relay (BaseException so the worker's ``except Exception`` relay does
    not catch it — a hard crash sends nothing)."""


@dataclass
class Fault:
    kind: str
    params: Dict[str, Any] = field(default_factory=dict)
    sticky: bool = False

    def param(self, key: str, default: Any = None) -> Any:
        return self.params.get(key, default)


@dataclass
class ChaosSpec:
    """A parsed fault plan (picklable — it rides Process args)."""

    faults: List[Fault] = field(default_factory=list)

    @classmethod
    def parse(cls, text: str) -> "ChaosSpec":
        faults: List[Fault] = []
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            name, *kvs = part.split(":")
            name = name.strip()
            if name not in _KNOWN:
                raise ValueError(
                    f"unknown chaos fault {name!r} (known: {sorted(_KNOWN)})"
                )
            params: Dict[str, Any] = {}
            for kv in kvs:
                k, _, v = kv.partition("=")
                k = k.strip()
                v = v.strip()
                try:
                    params[k] = int(v)
                except ValueError:
                    try:
                        params[k] = float(v)
                    except ValueError:
                        params[k] = v
            sticky = bool(params.pop("sticky", name == "poison_shard"))
            faults.append(Fault(name, params, sticky))
        return cls(faults)

    @classmethod
    def from_env(cls) -> Optional["ChaosSpec"]:
        raw = os.environ.get(CHAOS_ENV, "").strip()
        return cls.parse(raw) if raw else None

    def respawn_view(self) -> Optional["ChaosSpec"]:
        """The spec a RESPAWNED worker receives: sticky faults only.
        One-shot faults model transient failures — the respawn is the
        recovery, so it must not re-fire them."""
        sticky = [f for f in self.faults if f.sticky]
        return ChaosSpec(sticky) if sticky else None


class WorkerChaos:
    """Per-worker-incarnation injection state; every hook is a no-op
    when none of the spec's faults target this worker."""

    def __init__(self, spec: ChaosSpec, worker_id: int, is_process: bool):
        self.worker_id = worker_id
        self.is_process = is_process
        self.faults = [
            f for f in spec.faults
            if f.param("worker") is None or f.param("worker") == worker_id
        ]
        self.batches_emitted = 0
        self.shard_emitted = 0
        self.current_shard = -1
        self.descriptors_sent = 0
        self.overflows_forced = 0

    # -- death ----------------------------------------------------------

    def _die(self, mode: str) -> None:
        if mode == "soft":
            raise RuntimeError(
                f"chaos: injected worker {self.worker_id} failure"
            )
        if self.is_process:
            os._exit(23)  # a real crash: no relay, no teardown
        raise _ChaosHardExit()  # threads: silent unwind, no DONE/ERROR

    def on_shard_start(self, shard_index: int) -> None:
        self.current_shard = shard_index
        self.shard_emitted = 0

    def before_batch(self) -> None:
        """Called before framing each batch — the kill/poison window."""
        for f in self.faults:
            if f.kind == "kill_worker" and \
                    self.batches_emitted >= int(f.param("after", 0)):
                self._die(f.param("mode", "hard"))
            if f.kind == "poison_shard" and \
                    f.param("shard") == self.current_shard and \
                    self.shard_emitted >= int(f.param("after", 0)):
                self._die(f.param("mode", "hard"))

    def after_emit(self) -> None:
        self.batches_emitted += 1
        self.shard_emitted += 1

    # -- transport-level faults -----------------------------------------

    def before_put(self) -> None:
        for f in self.faults:
            if f.kind == "delay_put":
                time.sleep(float(f.param("seconds", 0.05)))

    def corrupt(self, desc) -> None:
        """Scramble the targeted descriptor in place (then count it)."""
        for f in self.faults:
            if f.kind == "corrupt_descriptor" and \
                    self.descriptors_sent == int(f.param("index", 0)):
                if f.param("field", "generation") == "slot":
                    desc.slot = desc.slot + 1_000_000
                else:
                    desc.generation = desc.generation + 1_000_000
        self.descriptors_sent += 1

    def force_overflow(self) -> bool:
        for f in self.faults:
            if f.kind == "slot_overflow" and \
                    self.batches_emitted >= int(f.param("after", 0)):
                count = f.param("count")
                if count is None or self.overflows_forced < int(count):
                    self.overflows_forced += 1
                    return True
        return False

    def drop_done(self, shard_index: int) -> bool:
        for f in self.faults:
            if f.kind == "drop_done" and \
                    f.param("shard", shard_index) == shard_index:
                return True
        return False


class FrontChaos:
    """Front-tier fault injection (``logparser_tpu/front.py``): the
    fleet consults :meth:`on_routed` after every routed session and
    :meth:`on_ready` when a sidecar (re)reports ready.  Every hook is a
    no-op when the spec carries no front faults."""

    def __init__(self, spec: ChaosSpec):
        self.faults = [f for f in spec.faults if f.kind in FRONT_FAULTS]
        self.routed_to: Dict[int, int] = {}
        self._fired: set = set()
        self._flaps: Dict[int, int] = {}

    def __bool__(self) -> bool:
        return bool(self.faults)

    def on_routed(self, sidecar: int) -> Optional[str]:
        """One session landed on ``sidecar``; returns the injected
        action — ``"kill"`` / ``"wedge"`` — or None.  ``after=S`` fires
        right after the sidecar's S-th routed session; each fault fires
        once."""
        n = self.routed_to[sidecar] = self.routed_to.get(sidecar, 0) + 1
        for idx, f in enumerate(self.faults):
            if idx in self._fired:
                continue
            if f.kind not in ("kill_sidecar", "wedge_sidecar"):
                continue
            if int(f.param("index", sidecar)) != sidecar:
                continue
            if n > int(f.param("after", 0)):
                self._fired.add(idx)
                return "kill" if f.kind == "kill_sidecar" else "wedge"
        return None

    def wedge_seconds(self, sidecar: int) -> Optional[float]:
        """The SIGCONT delay of the wedge aimed at ``sidecar`` (None =
        stay stopped until the supervisor kills it)."""
        for f in self.faults:
            if f.kind == "wedge_sidecar" and \
                    int(f.param("index", sidecar)) == sidecar:
                sec = f.param("seconds")
                return float(sec) if sec is not None else None
        return None

    def on_ready(self, sidecar: int) -> bool:
        """Whether a flap fault wants this freshly-ready sidecar killed
        again (``count`` bounds the loop so drills terminate)."""
        for idx, f in enumerate(self.faults):
            if f.kind != "flap_sidecar":
                continue
            if int(f.param("index", sidecar)) != sidecar:
                continue
            n = self._flaps.get(idx, 0)
            if n < int(f.param("count", 3)):
                self._flaps[idx] = n + 1
                return True
        return False


class DeviceChaos:
    """Device-tier fault injection (``tpu/batch.py``'s fault layer,
    docs/FAULTS.md): :meth:`on_execute` is consulted once per device
    execution — the dispatch+fetch of one padded batch, including each
    bisected retry chunk — and either raises an injected typed fault
    (oom/compile) or returns seconds to wedge (the execution sleeps, so
    an armed deadline expires exactly like a hung kernel).  Every hook
    is a no-op when the spec carries no device faults.  jax-free: the
    typed faults import from ``tpu.device_faults``, which never touches
    the device runtime."""

    def __init__(self, spec: ChaosSpec):
        self.faults = [f for f in spec.faults if f.kind in DEVICE_FAULTS]
        self._fired: Dict[int, int] = {}
        self.executions = 0

    def __bool__(self) -> bool:
        return bool(self.faults)

    def fired(self, kind: Optional[str] = None) -> int:
        """How many injections have fired (optionally of one kind) —
        drills assert recovery stopped re-triggering faults."""
        return sum(
            n for idx, n in self._fired.items()
            if kind is None or self.faults[idx].kind == kind
        )

    def on_execute(self, n_lines: int) -> Optional[float]:
        from ..tpu.device_faults import DeviceCompileError, DeviceOomError

        self.executions += 1
        for idx, f in enumerate(self.faults):
            fired = self._fired.get(idx, 0)
            if not f.sticky and fired >= int(f.param("count", 1)):
                continue
            if self.executions <= int(f.param("after", 0)):
                continue
            if f.kind == "oom_batch":
                if n_lines >= int(f.param("min_lines", 0)):
                    self._fired[idx] = fired + 1
                    raise DeviceOomError(
                        "chaos: injected RESOURCE_EXHAUSTED: out of "
                        f"memory executing a {n_lines}-line device batch"
                    )
            elif f.kind == "fail_compile":
                self._fired[idx] = fired + 1
                raise DeviceCompileError(
                    "chaos: injected XLA compilation failure"
                )
            elif f.kind == "wedge_device":
                self._fired[idx] = fired + 1
                return float(f.param("seconds", 30.0))
        return None


class PodChaos:
    """Pod-tier fault injection (``pod/runner.py`` subprocess mode):
    :meth:`preempt_plan` maps host index -> committed-shard count after
    which the pod runner SIGTERMs that host's jobs CLI — the cloud-TPU
    preemption-notice drill (docs/JOBS.md "Preemption")."""

    def __init__(self, spec: ChaosSpec):
        self.faults = [f for f in spec.faults if f.kind in POD_FAULTS]
        for f in self.faults:
            if f.kind == "preempt_host" and f.param("host") is None:
                # Fail LOUD at arm time: a silently-dropped fault reads
                # as a green drill that never ran.
                raise ValueError(
                    "preempt_host requires host=<index> (which pod "
                    "host to SIGTERM)"
                )

    def __bool__(self) -> bool:
        return bool(self.faults)

    def preempt_plan(self) -> Dict[int, int]:
        return {
            int(f.param("host")): int(f.param("after", 1))
            for f in self.faults if f.kind == "preempt_host"
        }


class WriterChaos:
    """Consumer-side I/O fault injection for the durable job writer
    (``logparser_tpu/jobs/writer.py``).  ``check(op, shard)`` raises the
    armed ``OSError`` when a fault matches — ``count`` bounds one-shot
    faults (the retry ladder must absorb them); ``sticky=1`` fires
    forever (the shard-must-fail drill)."""

    def __init__(self, spec: ChaosSpec):
        self.faults = [f for f in spec.faults if f.kind in IO_FAULTS]
        self._fired: Dict[int, int] = {}

    def __bool__(self) -> bool:
        return bool(self.faults)

    def check(self, op: str, shard: int) -> None:
        import errno

        for idx, f in enumerate(self.faults):
            f_op = f.param("op")
            if f_op is not None and f_op != op:
                continue
            f_shard = f.param("shard")
            if f_shard is not None and f_shard != shard:
                continue
            fired = self._fired.get(idx, 0)
            if not f.sticky and fired >= int(f.param("count", 1)):
                continue
            self._fired[idx] = fired + 1
            code = errno.ENOSPC if f.kind == "enospc" else errno.EIO
            raise OSError(
                code,
                f"chaos: injected {f.kind} during {op} "
                f"(shard {shard})",
            )
