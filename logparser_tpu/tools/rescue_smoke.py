"""CI rescue smoke: the batched host-rescue pipeline on a dirty corpus.

Round 18 moved the escaped-quote class ON DEVICE (escape-parity mask in
``pipeline.compute_split``), so the smoke now drills BOTH sides of the
new boundary:

- leg 1 (the rescue machinery, still-host-rescued class): a small mixed
  stream with FORCED ~5% truncated >8k lines (the device judges only a
  prefix and always defers; the host parses the full line) plus the
  former overflow class (20-digit ``%b`` counters, on-device since
  round 9).  Asserts: truncated lines rescued byte-identically through
  the BATCHED rescue path, 20-digit values exact on device, rescue
  throughput/effective floors, ``oracle_routed_lines_total`` reasons on
  a live ``/metrics``.
- leg 2 (the escaped-quote class, device-decoded): a 5% forced
  escaped-quote corpus must route ZERO lines to the oracle
  (``oracle_routed_lines_total`` unchanged across the parse), deliver
  byte parity vs the per-line oracle, count every forced line in
  ``device_escaped_quote_lines_total``, and expose that counter on
  ``/metrics``.
- leg 3 (round 20, URI fields on device): with ``HTTP.PATH`` + a query
  key requested, a 5% forced repair-needing-URI corpus (fragment +
  ``;`` — repair stages the device cannot reproduce) must route EXACTLY
  those rows (reason ``device_reject``, zero ``host_fields`` — the
  covered URI set no longer forces whole-line oracle routing), move
  ``oracle_routed_lines_total`` by exactly that count, and deliver both
  URI fields byte-identically on the rescued AND the device-parsed rows.

Usage::

    make rescue-smoke
    python -m logparser_tpu.tools.rescue_smoke
"""
from __future__ import annotations

import os
import re
import sys
import time

# Rescue-pipeline throughput floor (rescued lines per rescue-wall
# second).  The truncated class carries ~8KB lines, so the floor is set
# below the escaped-quote era's 15k: the compiled+codegen oracle still
# clears ~8k of these on a weak CI core; a rescue path that
# re-serializes per line would trip it.
RESCUE_RATE_FLOOR = float(os.environ.get(
    "LOGPARSER_TPU_RESCUE_SMOKE_RATE_FLOOR", "5000"))
# Whole-batch effective floor — deliberately conservative: the smoke
# runs on CI CPUs; the real >=5M gate is bench.py's RESCUE_EFFECTIVE
# floor on the TPU host.
EFFECTIVE_FLOOR = float(os.environ.get(
    "LOGPARSER_TPU_RESCUE_SMOKE_EFFECTIVE_FLOOR", "2000"))

N_LINES = 2048
TRUNC_LEN = 8300          # > runtime.DEFAULT_MAX_LINE_LEN (8191)
FIELDS = ["IP:connection.client.host", "BYTES:response.body.bytes",
          "HTTP.USERAGENT:request.user-agent"]


def build_corpus():
    """Leg-1 corpus: 5% truncated >8k (host-rescued), 5% 20-digit %b
    (on-device), 90% clean."""
    from logparser_tpu.tools.demolog import generate_combined_lines

    base = generate_combined_lines(N_LINES, seed=90)
    truncated, overflow = [], []
    for i, ln in enumerate(base):
        if i % 20 == 0:  # 5%: truncated >8k, device defers, host rescues
            pad = "x" * max(1, TRUNC_LEN - len(ln))
            base[i] = re.sub(r'"([^"]*)"$', f'"trunc {pad} \\1"', ln,
                             count=1)
            truncated.append(i)
        elif i % 20 == 10:  # 5%: the FORMER overflow reject class
            base[i] = re.sub(r'" (\d{3}) (\d+|-) ',
                             f'" \\1 {10**19 + i} ', ln, count=1)
            overflow.append(i)
    return base, truncated, overflow


def build_escaped_corpus():
    """Leg-2 corpus: 5% forced escaped-quote user-agents — the class
    that must now route ZERO lines (device escape-parity decode)."""
    from logparser_tpu.tools.demolog import generate_combined_lines

    base = generate_combined_lines(N_LINES, seed=91)
    forced = []
    for i in range(0, len(base), 20):
        base[i] = re.sub(r'"([^"]*)"$', r'"esc \\" quote \1"', base[i],
                         count=1)
        forced.append(i)
    return base, forced


URI_FIELDS = FIELDS + ["HTTP.PATH:request.firstline.uri.path",
                       "STRING:request.firstline.uri.query.q"]


def build_uri_corpus():
    """Leg-3 corpus: 5% repair-needing URIs — a fragment plus a ``;``
    (HTML-entity unescape + fragment-artifact rewrites the device cannot
    reproduce) — the rest clean demolog traffic whose path + query keys
    dissect fully on device."""
    from logparser_tpu.tools.demolog import generate_combined_lines

    base = generate_combined_lines(N_LINES, seed=92)
    forced = []
    for i in range(0, len(base), 20):
        base[i] = re.sub(
            r'"(\S+) \S+ HTTP',
            r'"\1 /account;v=2/search?q=caf%C3%A9+x#top HTTP',
            base[i], count=1,
        )
        forced.append(i)
    return base, forced


def _routed_total() -> float:
    """Sum of oracle_routed_lines_total across reason labels."""
    from logparser_tpu.observability import counter_sum

    return counter_sum("oracle_routed_lines_total")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import urllib.request

    from logparser_tpu.core.exceptions import DissectionFailure
    from logparser_tpu.tpu.batch import TpuBatchParser, _CollectingRecord

    errors = []

    # ---- leg 1: the rescue machinery on the truncated class ----------
    lines, truncated, overflow = build_corpus()
    parser = TpuBatchParser("combined", FIELDS)
    parser.parse_batch(lines)  # warm: compile + caches

    t0 = time.perf_counter()
    result = parser.parse_batch(lines)
    wall = time.perf_counter() - t0

    reasons = result.rescue_reasons
    routed = result.oracle_rows
    # (a) widening guard: the 20-digit class must NOT route; the ONLY
    # routed lines are the truncated ones (reason "overflow").
    if reasons.get("device_reject", 0) or routed > len(truncated):
        errors.append(
            f"unexpected oracle routing: rows={routed} reasons={reasons} "
            f"(expected only the {len(truncated)} truncated lines)"
        )
    if reasons.get("overflow", 0) < len(truncated):
        errors.append(
            f"truncated lines not routed: {reasons} (expected >= "
            f"{len(truncated)} overflow)"
        )
    vals = result.to_pylist("BYTES:response.body.bytes")
    for i in overflow:
        if vals[i] != 10 ** 19 + i:
            errors.append(f"overflow row {i}: device value {vals[i]!r} != "
                          f"{10**19 + i}")
            break
    # (b) truncated lines rescued, bit-identical to the per-line oracle.
    ua = result.to_pylist("HTTP.USERAGENT:request.user-agent")
    for i in truncated[: 4]:
        try:
            rec = parser.oracle.parse(lines[i], _CollectingRecord())
            want = rec.values.get("HTTP.USERAGENT:request.user-agent")
        except DissectionFailure:
            errors.append(f"truncated line {i} not host-parseable")
            break
        if not result.valid[i] or ua[i] != want:
            errors.append(
                f"truncated row {i} not rescued bit-identically: "
                f"{(ua[i] or '')[:40]!r}... != {(want or '')[:40]!r}..."
            )
            break
    # (c) throughput floors.
    rescue_rate = (routed / result.rescue_wall_s
                   if result.rescue_wall_s else float("inf"))
    if rescue_rate < RESCUE_RATE_FLOOR:
        errors.append(
            f"rescue pipeline {rescue_rate:.0f} rescued-lines/s below "
            f"the {RESCUE_RATE_FLOOR:.0f} floor"
        )
    effective = len(lines) / wall if wall else float("inf")
    if effective < EFFECTIVE_FLOOR:
        errors.append(
            f"effective rate {effective:.0f} lines/s below the "
            f"{EFFECTIVE_FLOOR:.0f} smoke floor"
        )

    # ---- leg 2: the escaped-quote class must stay on device ----------
    esc_lines, forced = build_escaped_corpus()
    esc_parser = TpuBatchParser("combined", FIELDS)
    esc_parser.parse_batch(esc_lines)  # warm
    routed_before = _routed_total()
    esc_result = esc_parser.parse_batch(esc_lines)
    routed_after = _routed_total()
    if esc_result.oracle_rows or routed_after != routed_before:
        errors.append(
            "escaped-quote corpus routed lines to the oracle: "
            f"oracle_rows={esc_result.oracle_rows}, "
            f"oracle_routed_lines_total {routed_before} -> {routed_after} "
            "(must be unchanged — the class lives on device)"
        )
    if esc_result.escaped_quote_rows < len(forced):
        errors.append(
            f"device decoded {esc_result.escaped_quote_rows} < "
            f"{len(forced)} forced escaped-quote lines "
            "(device_escaped_quote_lines_total undercounts)"
        )
    esc_ua = esc_result.to_pylist("HTTP.USERAGENT:request.user-agent")
    for i in forced[: 8]:
        try:
            rec = esc_parser.oracle.parse(esc_lines[i], _CollectingRecord())
            want = rec.values.get("HTTP.USERAGENT:request.user-agent")
        except DissectionFailure:
            errors.append(f"escaped line {i} not host-parseable")
            break
        if not esc_result.valid[i] or esc_ua[i] != want:
            errors.append(
                f"escaped row {i} device decode not bit-identical to the "
                f"oracle: {esc_ua[i]!r} != {want!r}"
            )
            break

    # ---- leg 3: URI fields on device, repair-needing tail rescued ----
    uri_lines, uri_forced = build_uri_corpus()
    uri_parser = TpuBatchParser("combined", URI_FIELDS)
    uri_parser.parse_batch(uri_lines)  # warm
    uri_before = _routed_total()
    uri_result = uri_parser.parse_batch(uri_lines)
    uri_after = _routed_total()
    uri_reasons = uri_result.rescue_reasons
    if (uri_result.oracle_rows != len(uri_forced)
            or uri_reasons.get("host_fields", 0)
            or uri_reasons.get("device_reject", 0) != len(uri_forced)):
        errors.append(
            "URI leg routing off: "
            f"rows={uri_result.oracle_rows} reasons={uri_reasons} "
            f"(expected exactly the {len(uri_forced)} repair-needing "
            "URIs as device_reject, zero host_fields)"
        )
    if uri_after - uri_before != len(uri_forced):
        errors.append(
            f"oracle_routed_lines_total moved {uri_before} -> {uri_after} "
            f"(expected +{len(uri_forced)} for the forced URI rows)"
        )
    uri_cols = {f: uri_result.to_pylist(f) for f in URI_FIELDS[-2:]}
    # Byte parity on both sides of the boundary: rescued rows AND the
    # device-dissected neighbours.
    for i in uri_forced[: 6] + [j + 1 for j in uri_forced[: 6]]:
        try:
            rec = uri_parser.oracle.parse(uri_lines[i], _CollectingRecord())
        except DissectionFailure:
            errors.append(f"URI line {i} not host-parseable")
            break
        for fid, col in uri_cols.items():
            want = rec.values.get(fid)
            if not uri_result.valid[i] or col[i] != want:
                errors.append(
                    f"URI row {i} field {fid} not byte-identical: "
                    f"{col[i]!r} != {want!r}"
                )
                break
        else:
            continue
        break

    # (d) /metrics exposes the per-reason rescue counters AND the new
    # escaped-quote counter (live scrape, strict exposition grammar).
    from logparser_tpu.service import ParseService, ParseServiceClient
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    with ParseService(metrics_port=0) as svc:
        with ParseServiceClient(svc.host, svc.port, "combined",
                                FIELDS) as client:
            client.parse(lines[: 256])
            client.parse(esc_lines[: 256])
        url = f"http://{svc.host}:{svc.metrics_port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            text = resp.read().decode("utf-8")
    errors += validate_exposition(text)
    if ('logparser_tpu_oracle_routed_lines_total{reason="overflow"}'
            not in text):
        errors.append(
            "/metrics missing per-reason rescue counter "
            "oracle_routed_lines_total{reason=\"overflow\"}"
        )
    if "logparser_tpu_device_escaped_quote_lines_total" not in text:
        errors.append(
            "/metrics missing device_escaped_quote_lines_total "
            "(the escaped-quote decode counter)"
        )

    if errors:
        print(f"rescue smoke FAILED ({len(errors)} problems):")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(
        "rescue smoke OK: "
        f"{routed}/{len(lines)} routed ({reasons}), "
        f"rescue {rescue_rate:.0f} lines/s, "
        f"effective {effective:.0f} lines/s; "
        f"escaped-quote leg: 0 routed, "
        f"{esc_result.escaped_quote_rows} device-decoded; "
        f"URI leg: {uri_result.oracle_rows}/{len(uri_forced)} "
        "repair-needing rescued, 0 host_fields; "
        "/metrics well-formed"
    )
    return 0


if __name__ == "__main__":  # pragma: no cover — CLI
    sys.exit(main())
