"""CI rescue smoke: the batched host-rescue pipeline on a dirty corpus.

Builds a small mixed stream with FORCED ~5% device-rejected lines (a
backslash-escaped quote inside the user-agent: the host regex accepts
it, the optimistic device split does not) plus the former overflow
class (20-digit ``%b`` counters), then asserts the round-9 rescue
contract end to end:

- the overflow class stays ON DEVICE (full-int64 decoder: zero routed
  lines, exact values delivered) — the widening guard;
- the forced rejects are rescued with values identical to the per-line
  oracle, through the BATCHED rescue path;
- the rescue pipeline clears a throughput floor (rescued lines per
  second of rescue wall — load-independent of the device, so the smoke
  means the same thing on a CI CPU and a TPU host), and the batch's
  effective rate clears a conservative floor;
- a live ``/metrics`` scrape exposes the per-reason
  ``oracle_routed_lines_total`` counters and stays well-formed
  exposition (validated by metrics_smoke's strict grammar checker).

Usage::

    make rescue-smoke
    python -m logparser_tpu.tools.rescue_smoke
"""
from __future__ import annotations

import os
import re
import sys
import time

# Rescue-pipeline throughput floor (rescued lines per rescue-wall
# second).  The compiled+codegen oracle clears ~25k even on a weak CI
# core; the pre-round-4 generic engine (~10k) or a rescue path that
# re-serializes per line would trip it.
RESCUE_RATE_FLOOR = float(os.environ.get(
    "LOGPARSER_TPU_RESCUE_SMOKE_RATE_FLOOR", "15000"))
# Whole-batch effective floor — deliberately conservative: the smoke
# runs on CI CPUs; the real >=5M gate is bench.py's RESCUE_EFFECTIVE
# floor on the TPU host.
EFFECTIVE_FLOOR = float(os.environ.get(
    "LOGPARSER_TPU_RESCUE_SMOKE_EFFECTIVE_FLOOR", "10000"))

N_LINES = 2048
FIELDS = ["IP:connection.client.host", "BYTES:response.body.bytes",
          "HTTP.USERAGENT:request.user-agent"]


def build_corpus():
    from logparser_tpu.tools.demolog import generate_combined_lines

    base = generate_combined_lines(N_LINES, seed=90)
    forced, overflow = [], []
    for i, ln in enumerate(base):
        if i % 20 == 0:  # 5%: forced device-reject, host-rescued
            base[i] = re.sub(r'"([^"]*)"$', r'"esc \\" quote \1"', ln,
                             count=1)
            forced.append(i)
        elif i % 20 == 10:  # 5%: the FORMER overflow reject class
            base[i] = re.sub(r'" (\d{3}) (\d+|-) ',
                             f'" \\1 {10**19 + i} ', ln, count=1)
            overflow.append(i)
    return base, forced, overflow


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import urllib.request

    from logparser_tpu.core.exceptions import DissectionFailure
    from logparser_tpu.tpu.batch import TpuBatchParser, _CollectingRecord

    lines, forced, overflow = build_corpus()
    parser = TpuBatchParser("combined", FIELDS)
    parser.parse_batch(lines)  # warm: compile + caches

    t0 = time.perf_counter()
    result = parser.parse_batch(lines)
    wall = time.perf_counter() - t0

    errors = []
    reasons = result.rescue_reasons
    # (a) widening guard: the overflow class must NOT route.
    routed = result.oracle_rows
    if reasons.get("overflow", 0) or routed > len(forced):
        errors.append(
            f"former overflow class routed to the oracle: rows={routed} "
            f"reasons={reasons} (expected only the {len(forced)} forced "
            "rejects)"
        )
    vals = result.to_pylist("BYTES:response.body.bytes")
    for i in overflow:
        if vals[i] != 10 ** 19 + i:
            errors.append(f"overflow row {i}: device value {vals[i]!r} != "
                          f"{10**19 + i}")
            break
    # (b) forced rejects rescued, bit-identical to the per-line oracle.
    if reasons.get("device_reject", 0) < len(forced):
        errors.append(
            f"forced rejects not routed: {reasons} (expected >= "
            f"{len(forced)} device_reject)"
        )
    ua = result.to_pylist("HTTP.USERAGENT:request.user-agent")
    for i in forced[: 8]:
        try:
            rec = parser.oracle.parse(lines[i], _CollectingRecord())
            want = rec.values.get("HTTP.USERAGENT:request.user-agent")
        except DissectionFailure:
            errors.append(f"forced line {i} not host-parseable")
            break
        if not result.valid[i] or ua[i] != want:
            errors.append(
                f"forced row {i} not rescued bit-identically: "
                f"{ua[i]!r} != {want!r}"
            )
            break
    # (c) throughput floors.
    rescue_rate = (routed / result.rescue_wall_s
                   if result.rescue_wall_s else float("inf"))
    if rescue_rate < RESCUE_RATE_FLOOR:
        errors.append(
            f"rescue pipeline {rescue_rate:.0f} rescued-lines/s below "
            f"the {RESCUE_RATE_FLOOR:.0f} floor"
        )
    effective = len(lines) / wall if wall else float("inf")
    if effective < EFFECTIVE_FLOOR:
        errors.append(
            f"effective rate {effective:.0f} lines/s below the "
            f"{EFFECTIVE_FLOOR:.0f} smoke floor"
        )

    # (d) /metrics exposes the per-reason rescue counters (live scrape,
    # strict exposition grammar — reuses metrics_smoke's validator).
    from logparser_tpu.service import ParseService, ParseServiceClient
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    with ParseService(metrics_port=0) as svc:
        with ParseServiceClient(svc.host, svc.port, "combined",
                                FIELDS) as client:
            client.parse(lines[: 256])
        url = f"http://{svc.host}:{svc.metrics_port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            text = resp.read().decode("utf-8")
    errors += validate_exposition(text)
    if ('logparser_tpu_oracle_routed_lines_total{reason="device_reject"}'
            not in text):
        errors.append(
            "/metrics missing per-reason rescue counter "
            "oracle_routed_lines_total{reason=\"device_reject\"}"
        )

    if errors:
        print(f"rescue smoke FAILED ({len(errors)} problems):")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(
        "rescue smoke OK: "
        f"{routed}/{len(lines)} routed ({reasons}), "
        f"rescue {rescue_rate:.0f} lines/s, "
        f"effective {effective:.0f} lines/s, /metrics well-formed"
    )
    return 0


if __name__ == "__main__":  # pragma: no cover — CLI
    sys.exit(main())
