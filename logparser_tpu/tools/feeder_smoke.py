"""CI feeder smoke: sharded multi-worker framing == single-process parse_blob.

Runs the real ingest fabric (2 feeder workers, process mode with the
thread fallback, across 2 shard sizes) over a small demolog corpus and
fails (exit 1) unless:

- framing byte-parity holds: the concatenated batch payloads equal the
  corpus, and the concatenated encoded buffers equal one-shot
  ``encode_blob`` over the whole corpus;
- parse parity holds: ``FeederPool.feed(parser)`` tables concatenate to
  exactly ``parser.parse_blob``'s table (values, validity, counters);
- the ``feeder_*`` metric families land in the registry and the
  rendered Prometheus exposition stays structurally valid
  (:func:`logparser_tpu.tools.metrics_smoke.validate_exposition`).

Usage::

    make feeder-smoke
    python -m logparser_tpu.tools.feeder_smoke
"""
from __future__ import annotations

import sys

N_LINES = 4096
BATCH_LINES = 1024
WORKERS = 2
LINE_LEN = 256
FIELDS = [
    "IP:connection.client.host",
    "STRING:request.status.last",
    "BYTES:response.body.bytes",
]


def main() -> int:
    import numpy as np
    import pyarrow as pa

    from logparser_tpu.feeder import FeederPool
    from logparser_tpu.native import encode_blob
    from logparser_tpu.observability import metrics
    from logparser_tpu.tools.demolog import generate_combined_lines
    from logparser_tpu.tools.metrics_smoke import validate_exposition
    from logparser_tpu.tpu.batch import TpuBatchParser

    lines = generate_combined_lines(N_LINES, seed=11, garbage_fraction=0.01)
    blob = "\n".join(lines).encode()
    ref_buf, ref_lengths, _ = encode_blob(blob, line_len=LINE_LEN)

    parser = TpuBatchParser("combined", FIELDS)
    ref = parser.parse_blob(blob)
    ref_table = ref.to_arrow(include_validity=True, strings="copy")

    failures = []
    shard_sizes = (max(1, -(-len(blob) // WORKERS)), 64 << 10)
    for shard_bytes in shard_sizes:
        # Pass 1: framing byte-parity on the raw batch stream.
        pool = FeederPool(
            [blob], workers=WORKERS, shard_bytes=shard_bytes,
            batch_lines=BATCH_LINES, line_len=LINE_LEN,
        )
        ebs = list(pool.batches())
        mode = pool.stats()["mode"]
        if b"".join(e.payload for e in ebs) != blob:
            failures.append(f"shard_bytes={shard_bytes}: payload bytes "
                            "diverge from the corpus")
        buf = np.concatenate([e.buf for e in ebs])
        lengths = np.concatenate([e.lengths for e in ebs])
        if not (np.array_equal(buf, ref_buf)
                and np.array_equal(lengths, ref_lengths)):
            failures.append(f"shard_bytes={shard_bytes}: encoded buffers "
                            "diverge from one-shot encode_blob")

        # Pass 2: parse parity through the device consumer.
        pool = FeederPool(
            [blob], workers=WORKERS, shard_bytes=shard_bytes,
            batch_lines=BATCH_LINES, line_len=LINE_LEN,
        )
        tables = [
            r.to_arrow(include_validity=True, strings="copy")
            for r in pool.feed(parser)
        ]
        table = pa.concat_tables(tables).combine_chunks()
        if not table.equals(ref_table.combine_chunks()):
            failures.append(f"shard_bytes={shard_bytes}: feeder-fed Arrow "
                            "table diverges from parse_blob's")
        print(f"feeder-smoke: shard_bytes={shard_bytes} mode={mode} "
              f"batches={len(ebs)} rows={table.num_rows} OK")

    reg = metrics()
    for family in ("feeder_bytes_read_total", "feeder_lines_total",
                   "feeder_batches_total", "feeder_shards_total"):
        if reg.get(family) <= 0:
            failures.append(f"metric family missing/zero: {family}")
    text = reg.prometheus_text()
    for needle in ('logparser_tpu_stage_seconds_bucket{stage="feeder_encode"',
                   'logparser_tpu_stage_seconds_bucket{stage="feeder_read"',
                   "logparser_tpu_feeder_bytes_read_total"):
        if needle not in text:
            failures.append(f"/metrics exposition missing: {needle}")
    failures.extend(validate_exposition(text))

    if failures:
        print("FEEDER SMOKE FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print(f"feeder-smoke OK: {N_LINES} lines x {WORKERS} workers x "
          f"{len(shard_sizes)} shard sizes, byte- and parse-parity held")
    return 0


if __name__ == "__main__":
    sys.exit(main())
