"""CI feeder smoke: sharded multi-worker framing == single-process parse_blob.

Runs the real ingest fabric (2 feeder workers, process mode with the
thread fallback, across 2 shard sizes, over BOTH transports — the
zero-copy shared-memory ring and the pickled escape hatch) over a small
demolog corpus and fails (exit 1) unless:

- framing byte-parity holds on each transport: the concatenated batch
  payloads equal the corpus, and the concatenated encoded buffers equal
  one-shot ``encode_blob`` over the whole corpus;
- parse parity holds: ``FeederPool.feed(parser)`` tables concatenate to
  exactly ``parser.parse_blob``'s table (values, validity, counters);
- in process mode the ring transport actually engaged (descriptors over
  shared-memory slots, not a silent pickle fallback) and NO shared-
  memory segment leaks past pool teardown (``/dev/shm`` carries no
  ``lpring_*`` entries afterwards);
- the ``feeder_*`` metric families (ring counters included) land in the
  registry and the rendered Prometheus exposition stays structurally
  valid (:func:`logparser_tpu.tools.metrics_smoke.validate_exposition`).

Usage::

    make feeder-smoke
    python -m logparser_tpu.tools.feeder_smoke
"""
from __future__ import annotations

import os
import sys

N_LINES = 4096
BATCH_LINES = 1024
WORKERS = 2
LINE_LEN = 256
FIELDS = [
    "IP:connection.client.host",
    "STRING:request.status.last",
    "BYTES:response.body.bytes",
]
SHM_DIR = "/dev/shm"


def _ring_segments():
    from logparser_tpu.feeder import RING_NAME_PREFIX

    if not os.path.isdir(SHM_DIR):
        return None  # platform without a visible shm mount: skip the check
    return sorted(
        f for f in os.listdir(SHM_DIR) if f.startswith(RING_NAME_PREFIX)
    )


def main() -> int:
    import numpy as np
    import pyarrow as pa

    from logparser_tpu.feeder import FeederPool, ring_available
    from logparser_tpu.native import encode_blob
    from logparser_tpu.observability import metrics
    from logparser_tpu.tools.demolog import generate_combined_lines
    from logparser_tpu.tools.metrics_smoke import validate_exposition
    from logparser_tpu.tpu.batch import TpuBatchParser

    lines = generate_combined_lines(N_LINES, seed=11, garbage_fraction=0.01)
    blob = "\n".join(lines).encode()
    ref_buf, ref_lengths, _ = encode_blob(blob, line_len=LINE_LEN)

    parser = TpuBatchParser("combined", FIELDS)
    ref = parser.parse_blob(blob)
    ref_table = ref.to_arrow(include_validity=True, strings="copy")

    failures = []
    segments_before = _ring_segments()
    shard_sizes = (max(1, -(-len(blob) // WORKERS)), 64 << 10)
    transports = ("ring", "pickle") if ring_available() else ("pickle",)
    modes = set()
    for transport in transports:
        for shard_bytes in shard_sizes:
            tag = f"transport={transport} shard_bytes={shard_bytes}"
            # Pass 1: framing byte-parity on the raw batch stream.
            pool = FeederPool(
                [blob], workers=WORKERS, shard_bytes=shard_bytes,
                batch_lines=BATCH_LINES, line_len=LINE_LEN,
                transport=transport,
            )
            ebs = list(pool.batches())
            stats = pool.stats()
            mode = stats["mode"]
            modes.add(mode)
            if mode == "process" and stats["transport"] != transport:
                failures.append(
                    f"{tag}: requested transport did not engage "
                    f"(ran {stats['transport']!r})"
                )
            if b"".join(bytes(e.payload) for e in ebs) != blob:
                failures.append(f"{tag}: payload bytes diverge from the "
                                "corpus")
            buf = np.concatenate([e.buf for e in ebs])
            lengths = np.concatenate([e.lengths for e in ebs])
            if not (np.array_equal(buf, ref_buf)
                    and np.array_equal(lengths, ref_lengths)):
                failures.append(f"{tag}: encoded buffers diverge from "
                                "one-shot encode_blob")

            # Pass 2: parse parity through the device consumer (the
            # zero-copy flavor: slots release after materialization).
            pool = FeederPool(
                [blob], workers=WORKERS, shard_bytes=shard_bytes,
                batch_lines=BATCH_LINES, line_len=LINE_LEN,
                transport=transport,
            )
            tables = [
                r.to_arrow(include_validity=True, strings="copy")
                for r in pool.feed(parser)
            ]
            table = pa.concat_tables(tables).combine_chunks()
            if not table.equals(ref_table.combine_chunks()):
                failures.append(f"{tag}: feeder-fed Arrow table diverges "
                                "from parse_blob's")
            print(f"feeder-smoke: {tag} mode={mode} batches={len(ebs)} "
                  f"rows={table.num_rows} OK")

    # Shared-memory hygiene: every arena created above must be unlinked
    # by pool teardown — a leaked segment is an unbounded /dev/shm drip
    # on a long-lived serving host.
    segments_after = _ring_segments()
    if segments_before is not None and segments_after is not None:
        leaked = sorted(set(segments_after) - set(segments_before))
        if leaked:
            failures.append(f"leaked shared-memory segments: {leaked}")

    reg = metrics()
    for family in ("feeder_bytes_read_total", "feeder_lines_total",
                   "feeder_batches_total", "feeder_shards_total"):
        if reg.get(family) <= 0:
            failures.append(f"metric family missing/zero: {family}")
    if "process" in modes and "ring" in transports:
        if reg.get("feeder_ring_bytes_inplace_total") <= 0:
            failures.append(
                "ring ran but feeder_ring_bytes_inplace_total stayed zero"
            )
    text = reg.prometheus_text()
    needles = ['logparser_tpu_stage_seconds_bucket{stage="feeder_encode"',
               'logparser_tpu_stage_seconds_bucket{stage="feeder_read"',
               "logparser_tpu_feeder_bytes_read_total"]
    if "process" in modes and "ring" in transports:
        needles += ["logparser_tpu_feeder_ring_slot_wait_seconds_total",
                    "logparser_tpu_feeder_ring_bytes_inplace_total"]
    for needle in needles:
        if needle not in text:
            failures.append(f"/metrics exposition missing: {needle}")
    failures.extend(validate_exposition(text))

    if failures:
        print("FEEDER SMOKE FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print(f"feeder-smoke OK: {N_LINES} lines x {WORKERS} workers x "
          f"{len(shard_sizes)} shard sizes x {len(transports)} transports, "
          f"byte- and parse-parity held, no leaked shm segments")
    return 0


if __name__ == "__main__":
    sys.exit(main())
