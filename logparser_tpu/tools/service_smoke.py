"""CI service smoke: overload shedding + graceful drain against a LIVE
sidecar (docs/SERVICE.md acceptance drill).

Boots an in-process :class:`~logparser_tpu.service.ParseService` with a
deliberately tiny admission budget, then:

1. **Overload burst** — `tools/loadgen.py` drives 2x the session budget.
   Asserts ZERO connection resets (every refusal is a structured ``BUSY``
   error frame), zero unstructured sheds, and that goodput still flowed
   (the admitted sessions were served while the rest shed).
2. **Exposition** — scrapes ``/metrics`` and requires the overload metric
   families (``service_shed_total{reason}``, active-session gauges) in a
   structurally valid exposition (`metrics_smoke.validate_exposition`).
3. **Drain drill** — with a session still OPEN, starts
   ``shutdown(drain=True)``: ``/readyz`` must flip to 503 ``draining``
   while ``/healthz`` stays 200, the in-flight session must still
   complete a request (drain finishes admitted work, never drops it),
   and after the drain no ``svc-sess-*`` thread may survive.

Usage::

    make service-smoke
    python -m logparser_tpu.tools.service_smoke
"""
from __future__ import annotations

import os
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List


def _http_status(url: str) -> int:
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


def main() -> int:
    # Shed/drain smoke, not a perf run: never acquire a TPU for this.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from logparser_tpu.service import ParseService, ParseServiceClient
    from logparser_tpu.tools.loadgen import make_lines, run_loadgen
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    problems: List[str] = []
    fields = ["IP:connection.client.host", "STRING:request.status.last"]
    lines = make_lines("combined", 64, seed=11)

    with ParseService(
        metrics_port=0,
        max_sessions=2,
        max_inflight=2,
        busy_retry_after_s=0.05,
        drain_deadline_s=15.0,
    ) as svc:
        # Warm both drill formats OUTSIDE the timed burst: a cold XLA
        # compile inside the window would measure the compiler.
        with ParseServiceClient(svc.host, svc.port, "combined",
                                fields) as warm:
            warm.parse(lines)
        with ParseServiceClient(
            svc.host, svc.port, '%h %l %u %t "%r" %>s %b',
            ["IP:connection.client.host", "BYTES:response.body.bytes"],
        ) as warm:
            warm.parse(make_lines("common", 64, seed=11))

        # 1) Overload burst: 2x the session budget.
        record = run_loadgen(
            svc.host, svc.port, clients=4, duration_s=2.0,
            batch_lines=64, burst=2, interval_s=0.02,
        )
        if record["resets"]:
            problems.append(
                f"{record['resets']} connection resets under overload "
                "(every refusal must be a structured BUSY frame)"
            )
        if record["busy"] == 0:
            problems.append(
                "overload burst at 2x session budget never shed "
                "(admission control is not engaging)"
            )
        if record["busy_unstructured"]:
            problems.append(
                f"{record['busy_unstructured']} BUSY frames carried "
                "unparseable detail JSON"
            )
        if record["ok"] == 0:
            problems.append("no request succeeded during the burst "
                            "(admitted sessions were not served)")

        # 2) /metrics must expose the overload families, well-formed.
        url = f"http://{svc.host}:{svc.metrics_port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            text = resp.read().decode("utf-8")
        problems.extend(validate_exposition(text))
        for needle in ("logparser_tpu_service_shed_total",
                       "logparser_tpu_service_sessions_active",
                       "logparser_tpu_service_requests_total"):
            if needle not in text:
                problems.append(f"required metric absent: {needle}")

        # 3) Drain drill: readyz flips while an open session finishes.
        base = f"http://{svc.host}:{svc.metrics_port}"
        if _http_status(base + "/readyz") != 200:
            problems.append("/readyz not 200 before drain")
        client = ParseServiceClient(svc.host, svc.port, "combined", fields)
        # One served request BEFORE the drain starts: proves the session
        # is admitted server-side, so the drill never races the accept
        # loop on a loaded CI box.
        client.parse(lines)
        drainer = threading.Thread(
            target=lambda: svc.shutdown(drain=True), daemon=True
        )
        drainer.start()
        flipped = False
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if _http_status(base + "/readyz") == 503:
                flipped = True
                break
            time.sleep(0.02)
        if not flipped:
            problems.append("/readyz never flipped to 503 during drain")
        if _http_status(base + "/healthz") != 200:
            problems.append("/healthz not 200 during drain (liveness must "
                            "hold while draining)")
        # New connections during the drain window get the STRUCTURED
        # draining shed (the listener stays up until admitted sessions
        # finish), never ECONNREFUSED.
        try:
            ParseServiceClient(
                svc.host, svc.port, "combined", fields
            ).parse(lines[:1])
            problems.append("a new session was admitted during drain")
        except Exception as e:  # noqa: BLE001 — classify below
            from logparser_tpu.service import ServiceBusyError

            if not (isinstance(e, ServiceBusyError)
                    and e.reason == "draining"):
                problems.append(
                    "new connection during drain did not shed "
                    f"BUSY(draining): {type(e).__name__}: {e}"
                )
        try:
            table = client.parse(lines)
            if table.num_rows != len(lines):
                problems.append("drained session returned a short table")
        except Exception as e:  # noqa: BLE001 — the drill must report, not die
            problems.append(
                f"in-flight session failed during drain: {type(e).__name__}: {e}"
            )
        client.close()
        drainer.join(timeout=20)
        if drainer.is_alive():
            problems.append("drain did not complete within its deadline")

    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("svc-sess-") and t.is_alive()]
    if leaked:
        problems.append(f"leaked session threads after drain: {leaked}")

    if problems:
        print(f"service smoke FAILED ({len(problems)} problems):")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(
        "service smoke OK: "
        f"{record['ok']} served / {record['busy']} structured sheds "
        f"({record['busy_reasons']}) / 0 resets; readyz flipped during "
        "drain; no leaked session threads"
    )
    return 0


if __name__ == "__main__":  # pragma: no cover — CLI
    sys.exit(main())
