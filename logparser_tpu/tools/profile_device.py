"""Device kernel profile for a compiled parser: where the milliseconds go.

``jax.profiler.trace`` works through the tunneled chip attachment and the
xplane protobuf is parseable with the in-image tensorflow (
``tensorflow.tsl.profiler.protobuf.xplane_pb2``), so this tool runs the
fused executor under the profiler and prints per-fusion device time —
ground truth the marginal-slope estimator in bench.py cannot give
(it is jitter- and floor-limited; see ROADMAP).

Set LOGPARSER_TPU_XPROF_STAGES=1 (or call
``logparser_tpu.enable_stage_annotations()``) before capturing and the
host planes of the same xplane trace carry ``lp.<stage>`` scopes named
exactly like the metrics registry's pipeline stages
(docs/OBSERVABILITY.md) — device fusions and host stages line up in one
timeline.

Usage::

    python -m logparser_tpu.tools.profile_device            # headline parser
    python -m logparser_tpu.tools.profile_device --batch 65536 --iters 10
"""
from __future__ import annotations

import glob
import os
import tempfile
from typing import Dict, List, Optional, Tuple

from .demolog import HEADLINE_FIELDS


def profile_parser(
    parser, lines, iters: int = 5, views: bool = False
) -> Optional[List[Tuple[str, float]]]:
    """Run the parser's fused executor under jax.profiler and return
    [(event name, total_ms)] for the device plane, descending; None when
    the xplane proto module is unavailable.  ``views=True`` profiles the
    parse_batch product path (device-emitted Arrow view rows included)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from ..tpu.runtime import encode_batch

    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:
        return None

    buf, lengths, _ = encode_batch(lines)
    fn = parser.device_views_fn() if views else parser.device_fn()
    if fn is None:
        return []
    jb, jl = jnp.asarray(buf), jnp.asarray(lengths)
    np.asarray(fn(jb, jl))  # compile + warm
    import shutil

    out_dir = tempfile.mkdtemp(prefix="lpprof")
    try:
        with jax.profiler.trace(out_dir):
            for _ in range(iters):
                np.asarray(fn(jb, jl))

        totals: Dict[str, int] = {}
        for path in glob.glob(
            os.path.join(out_dir, "**", "*.xplane.pb"), recursive=True
        ):
            xs = xplane_pb2.XSpace()
            with open(path, "rb") as f:
                xs.ParseFromString(f.read())
            for plane in xs.planes:
                if (
                    "TPU" not in plane.name
                    and "device" not in plane.name.lower()
                ):
                    continue
                for line in plane.lines:
                    for ev in line.events:
                        name = plane.event_metadata[ev.metadata_id].name
                        totals[name] = totals.get(name, 0) + ev.duration_ps
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)
    return sorted(
        ((name, ps / 1e9) for name, ps in totals.items()),
        key=lambda kv: -kv[1],
    )


def main() -> None:  # pragma: no cover - CLI
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--format", default="combined")
    ap.add_argument("--fields", nargs="*", default=None)
    args = ap.parse_args()

    from .demolog import generate_combined_lines
    from ..tpu.batch import TpuBatchParser

    parser = TpuBatchParser(args.format, args.fields or HEADLINE_FIELDS)
    lines = generate_combined_lines(args.batch, seed=42)
    prof = profile_parser(parser, lines, iters=args.iters)
    if prof is None:
        print("xplane proto module unavailable (needs tensorflow)")
        return
    if not prof:
        print("no device events")
        return
    # The largest event is the jit module envelope (it nests the fusions
    # listed below — summing everything would double-count).
    envelope_ms = prof[0][1]
    per_iter = envelope_ms / args.iters
    print(
        f"module envelope {envelope_ms:.2f} ms over {args.iters} iters "
        f"({per_iter:.3f} ms/batch of {args.batch} -> "
        f"{args.batch / per_iter * 1000:,.0f} lines/s kernel-time)"
    )
    for name, ms in prof[: args.top]:
        print(f"  {ms:9.3f} ms  {name[:100]}")


if __name__ == "__main__":  # pragma: no cover
    main()
