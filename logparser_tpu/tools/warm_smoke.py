"""CI warm-boot smoke: the cold-compile tax is actually gone
(docs/COMPILE.md acceptance drill).

Boots a REAL sidecar process twice against one persistent compile-cache
directory (``LOGPARSER_TPU_COMPILE_CACHE``):

1. **Cold boot** — empty cache: the first request pays lower + compile
   and the background prewarmer walks the bucket ladder (including the
   coalesced-batch shape), landing every rung in the cache.
2. **Warm boot** — same cache, fresh process: asserts the first request
   AND the full prewarm walk compile NOTHING (``parser_compile_total``
   ``{phase=lower}`` == 0 and ``{phase=compile}`` == 0 — deserialize
   only, counter-asserted over /metrics, never wall-clock), the prewarm
   covered every ladder rung including the coalesced shape with zero
   ``source="compiled"`` entries, the ARROW payload is byte-identical
   to the cold boot's, and the exposition validates
   (`metrics_smoke.validate_exposition`).

Usage::

    make warm-smoke
    python -m logparser_tpu.tools.warm_smoke
"""
from __future__ import annotations

import os
import re
import socket
import struct
import sys
import tempfile
import time
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

DRILL_FORMAT = "combined"
DRILL_FIELDS = [
    "IP:connection.client.host",
    "STRING:request.status.last",
    "BYTES:response.body.bytes",
]
DRILL_LINES = 64

# The exposition name prefix (observability.render_prometheus).
_PREFIX = "logparser_tpu_"


def _scrape(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode("utf-8")


def _family_values(text: str, family: str) -> Dict[str, float]:
    """``{label-block-or-'': value}`` for one exposition family."""
    pat = re.compile(
        r"^" + re.escape(_PREFIX + family) + r"(\{[^}]*\})? (\S+)$", re.M)
    return {m.group(1) or "": float(m.group(2))
            for m in pat.finditer(text)}


def _labeled(values: Dict[str, float], **labels: str) -> float:
    want = {f'{k}="{v}"' for k, v in labels.items()}
    total = 0.0
    for block, v in values.items():
        parts = set(p for p in block.strip("{}").split(",") if p)
        if want <= parts:
            total += v
    return total


def _request_arrow(host: str, port: int, config: bytes,
                   lines: Sequence[str], timeout_s: float) -> bytes:
    """One CONFIG + LINES round over a raw socket; returns the ARROW
    payload bytes (raises on an error frame / reset)."""
    payload = struct.pack(">I", len(lines)) + "\n".join(lines).encode()
    sock = socket.create_connection((host, port), timeout=timeout_s)
    try:
        sock.settimeout(timeout_s)
        sock.sendall(struct.pack(">I", len(config)) + config)
        sock.sendall(struct.pack(">I", len(payload)) + payload)

        def recv_exact(n: int) -> bytes:
            buf = bytearray()
            while len(buf) < n:
                chunk = sock.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError("sidecar reset mid-response")
                buf.extend(chunk)
            return bytes(buf)

        (n,) = struct.unpack(">I", recv_exact(4))
        if n == 0xFFFFFFFF:
            (m,) = struct.unpack(">I", recv_exact(4))
            raise RuntimeError(
                f"error frame: {recv_exact(m).decode('utf-8', 'replace')}")
        body = recv_exact(n)
        sock.sendall(struct.pack(">I", 0))
        return body
    finally:
        sock.close()


def boot_probe(cache_dir: str, *, lines: Sequence[str],
               log_format: str = DRILL_FORMAT,
               fields: Sequence[str] = tuple(DRILL_FIELDS),
               prewarm_buckets: Optional[str] = None,
               prewarm_line_len: Optional[int] = None,
               request_timeout_s: float = 300.0,
               prewarm_timeout_s: float = 300.0) -> Dict[str, Any]:
    """Boot one real sidecar against ``cache_dir``, time its first
    request, wait for the background prewarm walk to finish, scrape the
    compile/prewarm counters, and shut it down.

    Returns ``ready_s`` (spawn -> SIDECAR_READY), ``first_request_s``
    (CONFIG+LINES -> ARROW wall, parser build included), ``arrow`` (the
    payload bytes, for cross-boot parity), ``prewarm_done``, the counter
    dict, and the raw exposition text.  Reused by the bench's ``compile``
    section — the smoke's probe and the gated numbers are the same code.
    """
    import json as _json

    from logparser_tpu.front import ProcessSidecar

    env = {"LOGPARSER_TPU_COMPILE_CACHE": cache_dir}
    if prewarm_buckets is not None:
        env["LOGPARSER_TPU_PREWARM_BUCKETS"] = prewarm_buckets
    if prewarm_line_len is not None:
        env["LOGPARSER_TPU_PREWARM_LINE_LEN"] = str(prewarm_line_len)
    t0 = time.perf_counter()
    handle = ProcessSidecar(0, extra_args=["--max-sessions", "8"], env=env)
    ready_s = time.perf_counter() - t0
    try:
        config = _json.dumps({
            "log_format": log_format, "fields": list(fields),
            "timestamp_format": None,
        }).encode()
        t0 = time.perf_counter()
        arrow = _request_arrow(handle.host, handle.port, config, lines,
                               request_timeout_s)
        first_request_s = time.perf_counter() - t0
        # The prewarm walk runs off the request path; wait for its
        # completion tick so the scraped counters cover the WHOLE ladder
        # (and so a later boot against this cache finds every rung).
        url = f"http://{handle.host}:{handle.metrics_port}/metrics"
        deadline = time.monotonic() + prewarm_timeout_s
        text = ""
        prewarm_done = False
        while time.monotonic() < deadline:
            text = _scrape(url)
            runs = _family_values(text, "parser_prewarm_runs_total")
            errs = _family_values(text, "parser_prewarm_errors_total")
            if sum(runs.values()) + sum(errs.values()) >= 1:
                prewarm_done = sum(runs.values()) >= 1
                break
            time.sleep(0.25)
        compile_totals = _family_values(text, "parser_compile_total")
        shapes = _family_values(text, "parser_prewarm_shapes_total")
        counters = {
            "lower": _labeled(compile_totals, phase="lower"),
            "compile": _labeled(compile_totals, phase="compile"),
            "deserialize": _labeled(compile_totals, phase="deserialize"),
            "cache_hits": sum(_family_values(
                text, "compile_cache_hits_total").values()),
            "cache_misses": sum(_family_values(
                text, "compile_cache_misses_total").values()),
            "cache_errors": sum(_family_values(
                text, "compile_cache_errors_total").values()),
            "prewarm_shapes": sum(shapes.values()),
            "prewarm_compiled": _labeled(shapes, source="compiled"),
            "prewarm_errors": sum(_family_values(
                text, "parser_prewarm_errors_total").values()),
        }
        return {
            "ready_s": round(ready_s, 3),
            "first_request_s": round(first_request_s, 3),
            "arrow": arrow,
            "prewarm_done": prewarm_done,
            "counters": counters,
            "exposition": text,
        }
    finally:
        handle.terminate()


def main() -> int:
    # A boot-latency smoke, not a perf run: never acquire a TPU, and
    # every spawned sidecar inherits the same platform.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from logparser_tpu.tools.loadgen import make_lines
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    problems: List[str] = []
    lines = make_lines(DRILL_FORMAT, DRILL_LINES, seed=7)
    t_all = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="lptpu-warm-smoke-") as cache:
        cold = boot_probe(cache, lines=lines)
        print(f"warm-smoke: cold boot ready {cold['ready_s']:.1f}s, "
              f"first request {cold['first_request_s']:.1f}s, "
              f"counters {cold['counters']}")
        if cold["counters"]["compile"] < 1:
            problems.append(
                "cold boot compiled nothing — the cache was not empty "
                "or the AOT path is not engaged")
        if not cold["prewarm_done"]:
            problems.append(
                "cold boot: background prewarm never completed "
                f"(errors={cold['counters']['prewarm_errors']})")

        warm = boot_probe(cache, lines=lines)
        print(f"warm-smoke: warm boot ready {warm['ready_s']:.1f}s, "
              f"first request {warm['first_request_s']:.1f}s, "
              f"counters {warm['counters']}")
        c = warm["counters"]
        # THE gate: a warm boot compiles nothing — counter-asserted,
        # deserialize is the only phase allowed to move.
        if c["lower"] or c["compile"]:
            problems.append(
                f"warm boot compiled: lower={c['lower']:.0f} "
                f"compile={c['compile']:.0f} (must both be 0)")
        if c["deserialize"] < 1:
            problems.append("warm boot deserialized nothing — the "
                            "first request did not come from the cache")
        if not warm["prewarm_done"]:
            problems.append(
                "warm boot: background prewarm never completed "
                f"(errors={c['prewarm_errors']})")
        # Ladder coverage incl. the coalesced-batch shape: the default
        # ladder (DEFAULT_BUCKET_LADDER) + the coalesce_max_lines bucket
        # — all served from cache/memory, none compiled.
        from logparser_tpu.service import ServiceLimits
        from logparser_tpu.tpu.compile_cache import DEFAULT_BUCKET_LADDER
        expect = len(set(DEFAULT_BUCKET_LADDER)
                     | {ServiceLimits().coalesce_max_lines})
        if c["prewarm_shapes"] < expect:
            problems.append(
                f"warm boot prewarm covered {c['prewarm_shapes']:.0f} "
                f"shapes < {expect} (coalesced shape missing?)")
        if c["prewarm_compiled"]:
            problems.append(
                f"warm boot prewarm COMPILED "
                f"{c['prewarm_compiled']:.0f} shapes (must load them)")
        if warm["arrow"] != cold["arrow"]:
            problems.append("ARROW payload differs between cold and "
                            "warm boot (cache served a wrong kernel?)")
        expo_problems = validate_exposition(warm["exposition"])
        problems += [f"exposition: {p}" for p in expo_problems]

    wall = time.monotonic() - t_all
    if problems:
        print(f"warm-smoke: FAIL ({wall:.0f}s)")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(f"warm-smoke: PASS ({wall:.0f}s) — warm boot compiled "
          "nothing, prewarm covered the coalesced shape, payloads "
          "byte-identical")
    return 0


if __name__ == "__main__":
    sys.exit(main())
