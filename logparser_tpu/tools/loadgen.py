"""Loadgen + SLO harness for the sidecar parse service (docs/SERVICE.md).

ROADMAP item 4's measurement half: N concurrent clients drive a live
:class:`~logparser_tpu.service.ParseService` with bursty, open-loop-style
arrivals over MIXED formats, and every wire outcome is classified the way
an SLO cares about it:

- ``ok``            — ARROW frame back; latency recorded (p50/p99).
- ``busy``          — structured ``BUSY`` shed (the server refusing work
  the DEFINED way); ``busy_unstructured`` counts BUSY frames whose JSON
  detail failed to parse (must stay 0), ``busy_reasons`` breaks sheds
  down by the server's reason code.
- ``deadline``      — structured ``DEADLINE`` response (request expired
  server-side, session survived).
- ``errors``        — ordinary per-request error frames.
- ``resets``        — the FORBIDDEN outcome: a connection that died where
  a response frame was due (RST/EOF).  The bench gate holds this at 0
  under a 2x overload burst.

Arrival model: each client schedules bursts of ``burst`` back-to-back
requests every ``interval_s`` on the wall clock.  When the service is
slower than the schedule the client is already late and fires
immediately — the backlog IS the overload — which is the open-loop
property closed-loop harnesses lack (they politely slow down with the
server and hide the melt).

Used three ways: ``bench.py``'s ``service`` section (goodput-retention +
zero-reset gates), ``tools/service_smoke.py`` (CI), and standalone::

    python -m logparser_tpu.tools.loadgen --port 8123 --clients 8
"""
from __future__ import annotations

import json
import math
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..service import (
    RECONNECT_BUSY_REASONS,
    ParseServiceClient,
    ParseServiceError,
    ServiceBusyError,
    ServiceClosedError,
    ServiceDeadlineError,
)

#: (name, log_format, fields) triples the mixed-tenant drill rotates
#: through per client index — two real formats so the parser cache and
#: per-session compile reuse are part of what the SLO measures.
DEFAULT_FORMATS: Tuple[Tuple[str, str, List[str]], ...] = (
    ("combined", "combined",
     ["IP:connection.client.host", "STRING:request.status.last"]),
    ("common", '%h %l %u %t "%r" %>s %b',
     ["IP:connection.client.host", "BYTES:response.body.bytes"]),
)


def make_lines(format_name: str, n: int, seed: int = 7) -> List[str]:
    """A corpus for one of the DEFAULT_FORMATS entries."""
    from .demolog import generate_combined_lines, truncate_to_common

    lines = generate_combined_lines(n, seed=seed)
    if format_name == "common":
        lines = [truncate_to_common(ln) for ln in lines]
    return lines


@dataclass
class _ClientStats:
    requests: int = 0
    ok: int = 0
    busy: int = 0
    busy_unstructured: int = 0
    deadline: int = 0
    errors: int = 0
    resets: int = 0
    connect_errors: int = 0
    lines_ok: int = 0
    tenant: Optional[str] = None
    busy_reasons: Dict[str, int] = field(default_factory=dict)
    latencies: List[float] = field(default_factory=list)
    #: outcome class -> sampled trace ids (--trace; capped so a long
    #: window cannot bloat the record — enough to grep /tracez with).
    trace_ids: Dict[str, List[str]] = field(default_factory=dict)

    def note_trace(self, outcome: str, trace_id: Optional[str]) -> None:
        if not trace_id:
            return
        ids = self.trace_ids.setdefault(outcome, [])
        if len(ids) < _TRACE_IDS_CAP and trace_id not in ids:
            ids.append(trace_id)

    def merge(self, other: "_ClientStats") -> None:
        self.requests += other.requests
        self.ok += other.ok
        self.busy += other.busy
        self.busy_unstructured += other.busy_unstructured
        self.deadline += other.deadline
        self.errors += other.errors
        self.resets += other.resets
        self.connect_errors += other.connect_errors
        self.lines_ok += other.lines_ok
        for k, v in other.busy_reasons.items():
            self.busy_reasons[k] = self.busy_reasons.get(k, 0) + v
        self.latencies.extend(other.latencies)
        for k, ids in other.trace_ids.items():
            for tid in ids:
                self.note_trace(k, tid)


#: Per outcome class, how many example trace ids --trace keeps.
_TRACE_IDS_CAP = 8


#: Prometheus families the coalesce occupancy report reads
#: (docs/OBSERVABILITY.md "Continuous batching").
_COALESCE_PREFIX = "logparser_tpu_service_coalesce"


def scrape_metrics(url: str) -> Dict[str, float]:
    """Flat {series_name_with_labels: value} view of one Prometheus text
    exposition scrape (comment lines dropped)."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=10) as resp:
        text = resp.read().decode("utf-8")
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def coalesce_report(before: Dict[str, float],
                    after: Dict[str, float]) -> Dict[str, Any]:
    """The continuous-batching occupancy report for one loadgen window,
    from /metrics scrapes taken around it: formed batches, mean batch
    occupancy (fill fraction of the configured geometry), mean coalesced
    sessions per batch, mean queue wait — the server-side half of the
    SLO record (the outcome counts above are the client-side half)."""
    def delta(name: str) -> float:
        return after.get(name, 0.0) - before.get(name, 0.0)

    spb = _COALESCE_PREFIX + "d_sessions_per_batch"
    occ = _COALESCE_PREFIX + "_batch_occupancy"
    wait = _COALESCE_PREFIX + "_wait_seconds"
    batches = delta(spb + "_count")
    waits = delta(wait + "_count")
    return {
        "batches": int(batches),
        "mean_sessions_per_batch": round(delta(spb + "_sum") / batches, 3)
        if batches else None,
        "mean_batch_occupancy": round(delta(occ + "_sum") / batches, 4)
        if batches else None,
        "mean_wait_ms": round(delta(wait + "_sum") / waits * 1000.0, 3)
        if waits else None,
        "expired_in_queue": int(delta(
            "logparser_tpu_service_coalesce_expired_total")),
    }


def _drive_native(host: str, port: int, cfg: Tuple[str, str, List[str]],
                  lines: List[str], duration_s: float, timeout_s: float,
                  stats: _ClientStats, exe: str, workdir: str) -> None:
    """One client driven by the compiled C++ protocol client
    (native/svc_client.cc): closed-loop back-to-back requests for the
    window, outcomes merged from its JSON report.  The fast driver takes
    the Python client's GIL share out of the measurement loop — the
    loadgen process spends its cycles on the OTHER clients."""
    import json as _json
    import os
    import subprocess

    _name, log_format, fields = cfg
    config_path = os.path.join(workdir, f"config-{_name}.json")
    lines_path = os.path.join(workdir, f"lines-{_name}.txt")
    if not os.path.exists(config_path):
        with open(config_path, "w") as f:
            _json.dump({"log_format": log_format, "fields": fields,
                        "timestamp_format": None}, f)
    if not os.path.exists(lines_path):
        with open(lines_path, "w") as f:
            f.write("\n".join(lines))
    try:
        out = subprocess.run(
            [exe, "--host", host, "--port", str(port),
             "--config", config_path, "--lines", lines_path,
             "--duration", str(duration_s)],
            capture_output=True, text=True,
            timeout=duration_s + timeout_s + 10.0,
        )
        rec = _json.loads(out.stdout)
    except Exception:  # noqa: BLE001 — a dead driver reads as a reset
        stats.requests += 1
        stats.resets += 1
        return
    stats.ok += int(rec.get("ok", 0))
    stats.busy += int(rec.get("busy", 0))
    stats.deadline += int(rec.get("deadline", 0))
    stats.errors += int(rec.get("errors", 0))
    stats.resets += int(rec.get("resets", 0))
    stats.lines_ok += int(rec.get("lines_ok", 0))
    stats.requests += sum(int(rec.get(k, 0)) for k in
                          ("ok", "busy", "deadline", "errors", "resets"))
    stats.latencies.extend(
        ms / 1000.0 for ms in rec.get("latencies_ms", ())
    )


def _quiet_close(client: Optional[ParseServiceClient]) -> None:
    if client is not None:
        try:
            client.close()
        except OSError:
            pass


def _percentile_ms(latencies: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile in ms: rank ceil(q*n) (1-based), so p99 of
    100 samples is the 99th value, not the max.  The epsilon absorbs
    float noise in q*n (0.99 * 200 = 198.000...03 must stay rank 198)."""
    if not latencies:
        return None
    ordered = sorted(latencies)
    rank = math.ceil(q * len(ordered) - 1e-9)
    idx = max(0, min(len(ordered) - 1, rank - 1))
    return round(ordered[idx] * 1000.0, 3)


def _drive(host: str, port: int, cfg: Tuple[str, str, List[str]],
           lines: List[str], stop_at: float, interval_s: float, burst: int,
           timeout_s: float, rng: random.Random,
           stats: _ClientStats, trace: bool = False) -> None:
    _name, log_format, fields = cfg
    client: Optional[ParseServiceClient] = None
    trace_id: Optional[str] = None
    next_t = time.monotonic() + rng.uniform(0.0, interval_s)
    while time.monotonic() < stop_at:
        if client is None:
            traceparent = None
            if trace:
                # A fresh SAMPLED head per connection: the session's
                # requests join one trace, and the record names its id
                # under whichever outcome class the requests land in —
                # /tracez lookups start from here (docs/OBSERVABILITY.md
                # "Tracing").
                from ..tracing import new_trace_context

                ctx = new_trace_context(sampled=True)
                traceparent, trace_id = ctx.traceparent(), ctx.trace_id
            try:
                client = ParseServiceClient(
                    host, port, log_format, fields, timeout=timeout_s,
                    tenant=stats.tenant, traceparent=traceparent,
                )
            except OSError:
                stats.connect_errors += 1
                time.sleep(0.02)
                continue
        for _ in range(burst):
            if time.monotonic() >= stop_at:
                break
            stats.requests += 1
            t0 = time.monotonic()
            try:
                table = client.parse(lines)
            except ServiceBusyError as e:
                stats.busy += 1
                stats.note_trace("busy", trace_id)
                if not e.structured:
                    stats.busy_unstructured += 1
                stats.busy_reasons[e.reason] = (
                    stats.busy_reasons.get(e.reason, 0) + 1
                )
                if e.reason in RECONNECT_BUSY_REASONS:
                    # Connection-level shed: the server closes this
                    # socket by contract — reconnect (after the hint)
                    # to keep the overload pressure standing.  A
                    # failover reconnect is what lands the session on a
                    # LIVE sidecar behind a front tier (docs/SERVICE.md
                    # "Fleet").
                    _quiet_close(client)
                    client = None
                time.sleep(max(e.retry_after_s, 0.01) * rng.uniform(0.5, 1.5))
                break
            except ServiceDeadlineError:
                stats.deadline += 1
                stats.note_trace("deadline", trace_id)
            except ServiceClosedError:
                stats.resets += 1
                stats.note_trace("resets", trace_id)
                _quiet_close(client)
                client = None
                break
            except ParseServiceError:
                stats.errors += 1
                stats.note_trace("errors", trace_id)
            except OSError:
                stats.resets += 1
                stats.note_trace("resets", trace_id)
                _quiet_close(client)
                client = None
                break
            else:
                stats.ok += 1
                stats.note_trace("ok", trace_id)
                stats.lines_ok += table.num_rows
                stats.latencies.append(time.monotonic() - t0)
        # Open-loop pacing: the NEXT burst is due on the clock, not after
        # this one's responses; a late client fires immediately.
        next_t += interval_s
        now = time.monotonic()
        if next_t > now:
            time.sleep(min(next_t - now, max(0.0, stop_at - now)))
    _quiet_close(client)


def tenant_of(client_index: int, tenants: int) -> Optional[str]:
    """Skewed tenant assignment for the fairness drills: tenant ``t0``
    is the NOISY one (every even client), the rest share the odd
    clients round-robin — so quota enforcement visibly protects the
    quiet tenants from the loud one."""
    if tenants <= 0:
        return None
    if tenants == 1 or client_index % 2 == 0:
        return "t0"
    return f"t{1 + (client_index // 2) % (tenants - 1)}"


def run_loadgen(host: str, port: int, *, clients: int = 8,
                duration_s: float = 3.0, batch_lines: int = 128,
                burst: int = 4, interval_s: float = 0.05,
                formats: Optional[Sequence[Tuple[str, str, List[str]]]] = None,
                seed: int = 7, timeout_s: float = 30.0,
                metrics_url: Optional[str] = None,
                native: bool = False,
                tenants: int = 0,
                trace: bool = False,
                mid_run_fn: Optional[Any] = None,
                mid_run_at_s: Optional[float] = None) -> Dict[str, Any]:
    """Drive the service at ``host:port`` and return the SLO record:
    outcome counts, ok-request p50/p99 (ms), and goodput
    (ok lines per wall second).

    ``formats`` with a SINGLE entry is the many-small-clients shared-
    format scenario (every client on one parser cache key — the shape
    continuous batching coalesces, docs/SERVICE.md).  ``metrics_url``
    (the server's /metrics endpoint) adds a ``coalesce`` block with the
    server-side occupancy report for the window.  ``native=True`` runs
    each client through the compiled C++ protocol client
    (native/svc_client.cc) instead of the Python one — closed-loop
    back-to-back requests, no burst pacing — falling back to the Python
    driver when no toolchain is available.

    ``tenants`` > 0 assigns every client a tenant identity with SKEWED
    load (:func:`tenant_of`; the CONFIG ``tenant`` key the front
    tier's fairness quotas act on), and the record grows a per-tenant
    outcome table.  ``mid_run_fn`` runs ONCE on a helper thread at
    ``mid_run_at_s`` (default mid-window) — the rolling-restart-under-
    load trigger ``make fleet-smoke`` uses — and the record notes
    whether it completed inside the window."""
    fmts = list(formats or DEFAULT_FORMATS)
    corpora = {name: make_lines(name, batch_lines, seed=seed)
               for name, _lf, _f in fmts}
    per_client = [
        _ClientStats(tenant=tenant_of(i, tenants)) for i in range(clients)
    ]
    native_exe = None
    workdir = None
    if native:
        from ..native import svc_client_path

        native_exe = svc_client_path()
        if native_exe is not None:
            import tempfile

            workdir = tempfile.mkdtemp(prefix="loadgen-native-")
    before = scrape_metrics(metrics_url) if metrics_url else None
    t_start = time.monotonic()
    stop_at = t_start + duration_s
    mid_run: Optional[Dict[str, Any]] = None
    mid_timer: Optional[threading.Timer] = None
    if mid_run_fn is not None:
        at_s = (mid_run_at_s if mid_run_at_s is not None
                else duration_s / 2.0)
        mid_run = {"at_s": round(at_s, 3), "completed": False,
                   "error": None}

        def fire() -> None:
            try:
                mid_run_fn()
                mid_run["completed"] = True
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                mid_run["error"] = f"{type(e).__name__}: {e}"

        mid_timer = threading.Timer(at_s, fire)
        mid_timer.daemon = True
        mid_timer.start()
    threads = []
    for i in range(clients):
        cfg = fmts[i % len(fmts)]
        if native_exe is not None:
            t = threading.Thread(
                target=_drive_native,
                args=(host, port, cfg, corpora[cfg[0]], duration_s,
                      timeout_s, per_client[i], native_exe, workdir),
                name=f"loadgen-native-{i}", daemon=True,
            )
        else:
            t = threading.Thread(
                target=_drive,
                args=(host, port, cfg, corpora[cfg[0]], stop_at, interval_s,
                      burst, timeout_s, random.Random(seed * 1000 + i),
                      per_client[i], trace),
                name=f"loadgen-{i}", daemon=True,
            )
        t.start()
        threads.append(t)
    for t in threads:
        # Generous join slack: a client mid-request at stop_at finishes
        # that request (bounded by the socket timeout) before exiting.
        t.join(timeout=duration_s + timeout_s + 10.0)
    wall_s = time.monotonic() - t_start
    if mid_timer is not None:
        # Generous: a blocking mid-run action (a full fleet roll with
        # per-sidecar warmups) may legitimately outlive the window; the
        # join only lasts as long as the action actually takes.
        mid_timer.join(timeout=timeout_s + 600.0)
    total = _ClientStats()
    for s in per_client:
        total.merge(s)
    if workdir is not None:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    extra: Dict[str, Any] = {}
    if mid_run is not None:
        extra["mid_run"] = mid_run
    if tenants > 0:
        by_tenant: Dict[str, Dict[str, int]] = {}
        for s in per_client:
            t = by_tenant.setdefault(s.tenant or "default", {
                "clients": 0, "requests": 0, "ok": 0, "busy": 0,
                "tenant_quota_sheds": 0,
            })
            t["clients"] += 1
            t["requests"] += s.requests
            t["ok"] += s.ok
            t["busy"] += s.busy
            t["tenant_quota_sheds"] += s.busy_reasons.get(
                "tenant_quota", 0)
        extra["tenants"] = {k: by_tenant[k] for k in sorted(by_tenant)}
    if trace:
        # Example trace ids per outcome class (capped): the operator's
        # entry point into /tracez for exactly the requests that shed,
        # expired, or reset.
        extra["trace_ids"] = {
            k: total.trace_ids[k] for k in sorted(total.trace_ids)
        }
    if before is not None:
        extra["coalesce"] = coalesce_report(
            before, scrape_metrics(metrics_url))
    if native:
        extra["driver"] = "native" if native_exe is not None else "python"
    return {
        **extra,
        "clients": clients,
        "duration_s": round(wall_s, 3),
        "batch_lines": batch_lines,
        "burst": burst,
        "interval_s": interval_s,
        "formats": [name for name, _lf, _f in fmts],
        "requests": total.requests,
        "ok": total.ok,
        "busy": total.busy,
        "busy_unstructured": total.busy_unstructured,
        "busy_reasons": dict(sorted(total.busy_reasons.items())),
        "deadline": total.deadline,
        "errors": total.errors,
        "resets": total.resets,
        "connect_errors": total.connect_errors,
        "lines_ok": total.lines_ok,
        "goodput_lines_per_sec": round(total.lines_ok / wall_s, 1)
        if wall_s > 0 else 0.0,
        "p50_ms": _percentile_ms(total.latencies, 0.50),
        "p99_ms": _percentile_ms(total.latencies, 0.99),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: run one loadgen window against a live service and print the
    JSON record."""
    import argparse
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--batch-lines", type=int, default=128)
    ap.add_argument("--burst", type=int, default=4)
    ap.add_argument("--interval", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--shared-format", action="store_true",
        help="many-small-clients scenario: every client on ONE format "
             "(one parser cache key), the shape continuous batching "
             "coalesces",
    )
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="server /metrics port: adds the server-side coalesce "
             "occupancy report (batches, sessions/batch, occupancy, "
             "queue wait) to the record",
    )
    ap.add_argument(
        "--native", action="store_true",
        help="drive with the compiled C++ protocol client "
             "(native/svc_client.cc); falls back to the Python client "
             "when no toolchain is available",
    )
    ap.add_argument(
        "--tenants", type=int, default=0,
        help="assign clients skewed tenant identities (t0 = the noisy "
             "tenant); the record grows a per-tenant outcome table — "
             "the front tier's fairness-quota drill (docs/SERVICE.md "
             "\"Fleet\")",
    )
    ap.add_argument(
        "--trace", action="store_true",
        help="stamp a fresh SAMPLED traceparent on every client "
             "connection and report example trace ids per outcome "
             "class — the /tracez entry point for shed/expired/reset "
             "requests (Python driver only; docs/OBSERVABILITY.md "
             "\"Tracing\")",
    )
    ap.add_argument(
        "--roll", action="store_true",
        help="mid-run rolling-restart trigger: POST /rollz on "
             "--metrics-port (a front tier's fleet endpoint) at half "
             "the window — the zero-downtime restart-under-load drill",
    )
    args = ap.parse_args(argv)
    mid_run_fn = None
    if args.roll:
        if not args.metrics_port:
            ap.error("--roll needs --metrics-port (the front tier's "
                     "fleet endpoint serving POST /rollz)")

        def mid_run_fn() -> None:
            import urllib.request

            req = urllib.request.Request(
                f"http://{args.host}:{args.metrics_port}/rollz",
                method="POST", data=b"",
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()

    record = run_loadgen(
        args.host, args.port, clients=args.clients,
        duration_s=args.duration, batch_lines=args.batch_lines,
        burst=args.burst, interval_s=args.interval, seed=args.seed,
        formats=DEFAULT_FORMATS[:1] if args.shared_format else None,
        metrics_url=(
            f"http://{args.host}:{args.metrics_port}/metrics"
            if args.metrics_port else None
        ),
        native=args.native,
        tenants=args.tenants,
        trace=args.trace,
        mid_run_fn=mid_run_fn,
    )
    print(json.dumps(record, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover — CLI
    raise SystemExit(main())
