"""CI metrics smoke: scrape a live service ``/metrics`` and validate it.

Spins up an in-process :class:`logparser_tpu.service.ParseService` with the
Prometheus endpoint enabled, pushes one small batch (including a garbage
line, so the oracle-route counters move), scrapes ``/metrics`` over real
HTTP, and fails (exit 1) on malformed exposition or missing stage metrics.
The validator is deliberately strict line-grammar checking (names, label
blocks, histogram bucket monotonicity, ``+Inf`` terminal, count/sum
consistency) — a malformed exposition silently breaks every scraper.

Usage::

    make metrics-smoke
    python -m logparser_tpu.tools.metrics_smoke
"""
from __future__ import annotations

import os
import re
import sys
from typing import List

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABELS = r"\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\}"
_VALUE = r"(?:[-+]?(?:[0-9]*\.)?[0-9]+(?:[eE][-+]?[0-9]+)?|[-+]?Inf|NaN)"
_SAMPLE_RE = re.compile(rf"^({_NAME})({_LABELS})? ({_VALUE})(?: [0-9]+)?$")
_TYPE_RE = re.compile(
    rf"^# TYPE ({_NAME}) (counter|gauge|histogram|summary|untyped)$"
)
_HELP_RE = re.compile(rf"^# HELP ({_NAME}) .*$")
_LE_RE = re.compile(r'le="([^"]*)"')

# Metric families the acceptance bar requires a live sidecar to expose
# after one parsed batch (docs/OBSERVABILITY.md inventory).
REQUIRED_SUBSTRINGS = (
    'logparser_tpu_stage_seconds_bucket{stage="encode",le="+Inf"}',
    'logparser_tpu_stage_seconds_bucket{stage="device",le="+Inf"}',
    'logparser_tpu_stage_seconds_bucket{stage="fetch",le="+Inf"}',
    'logparser_tpu_stage_seconds_bucket{stage="columns",le="+Inf"}',
    'logparser_tpu_stage_seconds_bucket{stage="oracle_fallback",le="+Inf"}',
    'logparser_tpu_stage_seconds_bucket{stage="assembly",le="+Inf"}',
    'logparser_tpu_stage_seconds_bucket{stage="ipc",le="+Inf"}',
    "logparser_tpu_oracle_routed_lines_total",
    # Round-20 residual census: the per-field ledger of host_fields
    # routing (which requested fields still force whole-line oracle
    # routing).  On `combined` the census is now EMPTY — the protocol
    # split and the timezone string table moved the last residuals to
    # device (see FORBIDDEN_SUBSTRINGS) — so the ledger is driven below
    # by a custom format whose space-padded strftime day (`%e`) the
    # device time-layout compiler rejects: a genuinely host-only field.
    'logparser_tpu_host_field_lines_total{'
    'field="TIME.EPOCH:request.receive.time.begin.epoch"}',
    "logparser_tpu_device_escaped_quote_lines_total",
    "logparser_tpu_service_requests_total",
    "logparser_tpu_parse_lines_total",
    # Analytics pushdown (docs/ANALYTICS.md): the aggregate session the
    # smoke drives below must move the device-path batch counter, the
    # D2H shrinkage ledger, and the fused aggregate stage timer.
    'logparser_tpu_analytics_batches_total{path="device"}',
    "logparser_tpu_analytics_d2h_bytes_saved_total",
    'logparser_tpu_stage_seconds_bucket{stage="aggregate",le="+Inf"}',
    # Build identity (docs/OBSERVABILITY.md): every exposition carries
    # one build_info gauge labeling the package + jax versions.
    "logparser_tpu_build_info{",
)

# Label blocks that must NOT appear in the exposition: the combined
# session below requests HTTP.PROTOCOL[.VERSION] and TIME.ZONE — once
# the last host-only residuals on `combined`, both device-native since
# the protocol span split (tpu/postproc.py) and the timezone string
# table (tpu/timefields.py).  If either ever re-enters the census, the
# device lane regressed to whole-line oracle routing.
FORBIDDEN_SUBSTRINGS = (
    'logparser_tpu_host_field_lines_total{field="HTTP.PROTOCOL',
    'logparser_tpu_host_field_lines_total{field="TIME.ZONE',
)


def validate_exposition(text: str) -> List[str]:
    """Strict structural validation of Prometheus text exposition; returns
    a list of problems (empty = valid)."""
    errors: List[str] = []
    if not text.endswith("\n"):
        errors.append("exposition must end with a trailing newline")
    typed: dict = {}
    # Histogram series bookkeeping: (base, labels-minus-le) -> data.
    hist_buckets: dict = {}
    hist_counts: dict = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line) or _HELP_RE.match(line)
            if m is None:
                errors.append(f"line {i}: malformed comment: {line!r}")
            elif line.startswith("# TYPE"):
                typed[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {i}: malformed sample: {line!r}")
            continue
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        for base, suffix in ((name[: -len("_bucket")], "_bucket"),
                             (name[: -len("_sum")], "_sum"),
                             (name[: -len("_count")], "_count")):
            if name.endswith(suffix) and typed.get(base) == "histogram":
                series = (base, _LE_RE.sub("", labels))
                if suffix == "_bucket":
                    le = _LE_RE.search(labels)
                    if le is None:
                        errors.append(f"line {i}: bucket without le label")
                        break
                    bound = (float("inf") if le.group(1) == "+Inf"
                             else float(le.group(1)))
                    hist_buckets.setdefault(series, []).append(
                        (bound, float(value))
                    )
                elif suffix == "_count":
                    hist_counts[series] = float(value)
                break
        else:
            stripped = re.sub(r"(_bucket|_sum|_count)$", "", name)
            if name not in typed and stripped not in typed:
                errors.append(f"line {i}: sample {name!r} has no # TYPE")
    for series, buckets in hist_buckets.items():
        bounds = [b for b, _ in buckets]
        counts = [c for _, c in buckets]
        if bounds != sorted(bounds):
            errors.append(f"{series}: bucket bounds out of order")
        if counts != sorted(counts):
            errors.append(f"{series}: cumulative bucket counts decrease")
        if not bounds or bounds[-1] != float("inf"):
            errors.append(f"{series}: missing le=\"+Inf\" bucket")
        elif series in hist_counts and counts[-1] != hist_counts[series]:
            errors.append(
                f"{series}: +Inf bucket {counts[-1]} != _count "
                f"{hist_counts[series]}"
            )
    return errors


def main() -> int:
    # Format smoke, not a perf run: never acquire a TPU for this.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import urllib.request

    from logparser_tpu.service import ParseService, ParseServiceClient

    lines = [
        '1.2.3.4 - - [31/Dec/2012:23:49:40 +0100] '
        '"GET /i.html?x=1 HTTP/1.1" 200 512 "-" "smoke/1.0"',
        # Still-host-rescued class (truncated >8k line — the device
        # judges only a prefix and always defers to the host): routes to
        # the oracle, so oracle_routed_lines_total must move.  (An
        # escaped-quote user-agent no longer qualifies: the round-18
        # escape-parity mask keeps that class on device, like the
        # round-9 full-int64 decoder did for 20-digit %b.)
        '5.6.7.8 - - [31/Dec/2012:23:49:41 +0100] '
        f'"GET /big HTTP/1.1" 200 17 "-" "smoke {"x" * 8300} trunc/1.0"',
        # Device-decoded escaped quote (round 18): stays ON device and
        # moves device_escaped_quote_lines_total instead.
        '9.10.11.12 - - [31/Dec/2012:23:49:42 +0100] '
        '"GET /esc HTTP/1.1" 200 9 "-" "smoke \\" esc/1.0"',
    ]
    with ParseService(metrics_port=0) as svc:
        with ParseServiceClient(
            svc.host, svc.port, "combined",
            # BYTES requested so the 20-digit line exercises the oracle
            # rescue route (device limb decode fails, host Long succeeds).
            # HTTP.PROTOCOL[.VERSION] and TIME.ZONE — the round-20
            # host-only residuals — are requested ON PURPOSE: both are
            # device-native now, so neither may surface in the
            # host_field_lines_total census (FORBIDDEN_SUBSTRINGS).
            ["IP:connection.client.host", "BYTES:response.body.bytes",
             "HTTP.PROTOCOL:request.firstline.protocol",
             "HTTP.PROTOCOL.VERSION:request.firstline.protocol.version",
             "TIME.ZONE:request.receive.time.timezone"],
        ) as client:
            table = client.parse(lines)
            assert table.num_rows == len(lines)
        # Census drill: `combined` no longer has any host-only field, so
        # the per-field ledger is exercised with a custom format whose
        # space-padded strftime day (%e) the device time-layout compiler
        # rejects — TIME.EPOCH under it is genuinely host-only and must
        # route with reason=host_fields.
        with ParseServiceClient(
            svc.host, svc.port,
            "%h %l %u %{begin:%Y-%m-%e %H:%M:%S}t \"%r\" %>s %b",
            ["IP:connection.client.host",
             "TIME.EPOCH:request.receive.time.begin.epoch"],
        ) as census:
            table = census.parse([
                '1.2.3.4 - - 2012-03- 7 23:49:40 '
                '"GET /i.html HTTP/1.1" 200 512',
            ])
            assert table.num_rows == 1
        # One aggregate-mode session so the analytics_* families exist
        # before the scrape asserts them (the row session above never
        # touches the pushdown path).
        with ParseServiceClient(
            svc.host, svc.port, "combined",
            ["IP:connection.client.host", "BYTES:response.body.bytes"],
            aggregate=[{"op": "count"},
                       {"op": "sum", "field": "BYTES:response.body.bytes"}],
        ) as agg:
            state = agg.parse(lines)
            counts = [d["value"] for d in state.summary()
                      if d.get("op") == "count"]
            assert counts == [len(lines)], state.summary()
        url = f"http://{svc.host}:{svc.metrics_port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200, resp.status
            text = resp.read().decode("utf-8")

    errors = validate_exposition(text)
    for needle in REQUIRED_SUBSTRINGS:
        if needle not in text:
            errors.append(f"required metric absent: {needle}")
    for needle in FORBIDDEN_SUBSTRINGS:
        if needle in text:
            errors.append(
                f"device-native field re-entered the host census: {needle}")
    if errors:
        print(f"metrics smoke FAILED ({len(errors)} problems):")
        for e in errors:
            print(f"  - {e}")
        return 1
    n_lines = len([ln for ln in text.splitlines() if ln and not ln.startswith("#")])
    print(f"metrics smoke OK: {n_lines} samples, exposition well-formed")
    return 0


if __name__ == "__main__":  # pragma: no cover — CLI
    sys.exit(main())
