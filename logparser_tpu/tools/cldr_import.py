"""CLDR -> locale name tables for the timestamp engine.

The reference resolves localized month/day names and week rules through
``java.util.Locale`` — JDK 9+ defaults to CLDR data
(TimeStampDissector.java:73-78 setLocale; WeekFields.of(locale)
:455-459).  This importer generates the same tables from CLDR (via
Babel's vendored CLDR distribution) into a checked-in JSON data file —
``dissectors/cldr_names.json`` — that ``timelayout.LOCALES`` loads at
import time.  Adding a locale is a one-line edit to LOCALE_TAGS below
plus a regeneration run::

    python -m logparser_tpu.tools.cldr_import        # rewrites the JSON

The JSON is the source of truth at runtime (no Babel dependency);
tests/test_cldr_locales.py regenerates from Babel when it is available
and asserts the checked-in file has not drifted.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

# Tags to generate: tag -> (names source, week-data source).  Week data
# in CLDR is keyed by TERRITORY, so the week source always carries one
# (Babel does not resolve likely subtags; a bare "fr" would fall to the
# world default's min_days=1 where WeekFields.of(fr) gives 4).  The
# engine's bare "en" is the reference's Locale.UK default (English
# names, ISO weeks); its NAMES come from root "en" (Sep/AM), its weeks
# from en_GB.
LOCALE_TAGS: Dict[str, Tuple[str, str]] = {
    "en": ("en", "en_GB"),
    "en_gb": ("en", "en_GB"),
    "en_uk": ("en", "en_GB"),
    "en_us": ("en", "en_US"),
    "fr": ("fr", "fr_FR"), "de": ("de", "de_DE"), "es": ("es", "es_ES"),
    "it": ("it", "it_IT"), "nl": ("nl", "nl_NL"),
    "pt": ("pt", "pt_BR"), "pt_pt": ("pt_PT", "pt_PT"),
    "da": ("da", "da_DK"), "sv": ("sv", "sv_SE"), "nb": ("nb", "nb_NO"),
    "fi": ("fi", "fi_FI"), "is": ("is", "is_IS"),
    "pl": ("pl", "pl_PL"), "cs": ("cs", "cs_CZ"), "sk": ("sk", "sk_SK"),
    "hu": ("hu", "hu_HU"), "ro": ("ro", "ro_RO"), "tr": ("tr", "tr_TR"),
    "ru": ("ru", "ru_RU"), "uk": ("uk", "uk_UA"), "el": ("el", "el_GR"),
    "bg": ("bg", "bg_BG"), "ca": ("ca", "ca_ES"), "hr": ("hr", "hr_HR"),
    "sl": ("sl", "sl_SI"), "et": ("et", "et_EE"), "lv": ("lv", "lv_LV"),
    "lt": ("lt", "lt_LT"), "id": ("id", "id_ID"), "vi": ("vi", "vi_VN"),
    "ms": ("ms", "ms_MY"), "ja": ("ja", "ja_JP"), "ko": ("ko", "ko_KR"),
    "zh": ("zh", "zh_CN"), "zh_tw": ("zh_Hant_TW", "zh_Hant_TW"),
    "ar": ("ar", "ar_SA"), "he": ("he", "he_IL"), "th": ("th", "th_TH"),
    "hi": ("hi", "hi_IN"), "fa": ("fa", "fa_IR"), "sr": ("sr", "sr_RS"),
    "mk": ("mk", "mk_MK"), "sq": ("sq", "sq_AL"), "az": ("az", "az_AZ"),
    "kk": ("kk", "kk_KZ"), "ka": ("ka", "ka_GE"), "hy": ("hy", "hy_AM"),
    "sw": ("sw", "sw_KE"), "af": ("af", "af_ZA"), "eu": ("eu", "eu_ES"),
    "gl": ("gl", "gl_ES"), "bn": ("bn", "bn_BD"), "ta": ("ta", "ta_IN"),
}

# JDK-flavored pins where the vendored CLDR vintage differs from the
# name forms Java's formatter resolves (and the engine's locked tests
# assert): dotted Spanish/Dutch abbreviations, plain-space Spanish
# day-period spelling, uppercase AM/PM for nl.  Everything else comes
# straight from CLDR.
OVERRIDES: Dict[str, Dict] = {
    "es": {
        "months_short": ["ene.", "feb.", "mar.", "abr.", "may.", "jun.",
                         "jul.", "ago.", "sept.", "oct.", "nov.", "dic."],
        "days_short": ["lun.", "mar.", "mié.", "jue.", "vie.", "sáb.",
                       "dom."],
        "ampm": ["a. m.", "p. m."],
    },
    "nl": {
        "months_short": ["jan.", "feb.", "mrt.", "apr.", "mei", "jun.",
                         "jul.", "aug.", "sep.", "okt.", "nov.", "dec."],
        "ampm": ["AM", "PM"],
    },
}

DATA_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "dissectors", "cldr_names.json",
)


def generate_locale(tag: str, names_src: str, weeks_src: str) -> Dict:
    """One locale's tables from Babel's CLDR data (+ JDK pins)."""
    from babel import Locale

    loc = Locale.parse(names_src)
    weeks = Locale.parse(weeks_src)
    months = loc.months["format"]
    days = loc.days["format"]
    periods = loc.day_periods["format"]["abbreviated"]

    def month_list(style: str) -> List[str]:
        return [str(months[style][i]) for i in range(1, 13)]

    def day_list(style: str) -> List[str]:
        # CLDR day indices: 0=Monday .. 6=Sunday (Babel numbering).
        return [str(days[style][i]) for i in range(7)]

    out = {
        "source": names_src,
        "weeks_source": weeks_src,
        "months_short": month_list("abbreviated"),
        "months_full": month_list("wide"),
        "days_short": day_list("abbreviated"),
        "days_full": day_list("wide"),
        "ampm": [str(periods["am"]), str(periods["pm"])],
        # Babel: 0=Monday..6=Sunday; the engine uses ISO 1=Monday..7=Sunday.
        "week_first_day": int(weeks.first_week_day) + 1,
        "week_min_days": int(weeks.min_week_days),
    }
    out.update(OVERRIDES.get(tag, {}))
    return out


def generate_all() -> Dict[str, Dict]:
    return {
        tag: generate_locale(tag, names_src, weeks_src)
        for tag, (names_src, weeks_src) in sorted(LOCALE_TAGS.items())
    }


def main() -> None:
    data = generate_all()
    with open(DATA_PATH, "w", encoding="utf-8") as f:
        json.dump(data, f, ensure_ascii=False, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(data)} locales to {DATA_PATH}")


if __name__ == "__main__":
    main()
