"""Self-contained GeoIP test fixtures: a MaxMind-DB *writer* + generators.

The reference ships generated test databases
(GeoIP2-TestData/source-data/*.json rendered by write-test-data.pl); the
rebuild's GeoIP tests and bench config used that read-only checkout.  This
module removes the dependency: a minimal writer for the public MaxMind DB
file format spec v2.0 (the exact inverse of
:mod:`logparser_tpu.geoip.mmdb`) plus generators for the City / Country /
ASN / ISP databases carrying the same records the test suite asserts
(the Basjes test ranges: 80.100.47.0/24, 2001:980::/29).

Writer scope: disjoint networks, record size 24, no data-section pointer
compression beyond whole-record dedup — plenty for fixtures, not a
general-purpose production writer.
"""
from __future__ import annotations

import ipaddress
import os
import struct
from typing import Any, Dict, List, Optional, Tuple

_METADATA_MARKER = b"\xab\xcd\xefMaxMind.com"

_T_UTF8 = 2
_T_DOUBLE = 3
_T_BYTES = 4
_T_UINT16 = 5
_T_UINT32 = 6
_T_MAP = 7
_T_UINT64 = 9
_T_ARRAY = 11
_T_BOOL = 14


def _ctrl(type_num: int, size: int) -> bytes:
    """Control byte(s) for a type + payload size (spec §'Data field format')."""
    ext = b""
    if type_num > 7:
        ext = bytes([type_num - 7])
        type_num = 0
    if size < 29:
        return bytes([(type_num << 5) | size]) + ext
    if size < 29 + 256:
        return bytes([(type_num << 5) | 29]) + ext + bytes([size - 29])
    if size < 285 + 65536:
        return bytes([(type_num << 5) | 30]) + ext + (size - 285).to_bytes(2, "big")
    return bytes([(type_num << 5) | 31]) + ext + (size - 65821).to_bytes(3, "big")


def encode_value(value: Any) -> bytes:
    """Encode one Python value in the MaxMind data-section type format."""
    if isinstance(value, bool):
        # Bool stores its value in the size bits; type 14 is extended.
        return _ctrl(_T_BOOL, 1 if value else 0)
    if isinstance(value, str):
        raw = value.encode("utf-8")
        return _ctrl(_T_UTF8, len(raw)) + raw
    if isinstance(value, bytes):
        return _ctrl(_T_BYTES, len(value)) + value
    if isinstance(value, float):
        return _ctrl(_T_DOUBLE, 8) + struct.pack(">d", value)
    if isinstance(value, int):
        if value < 0:
            raise ValueError("negative ints not needed by the fixtures")
        if value < 1 << 16:
            raw = value.to_bytes((value.bit_length() + 7) // 8, "big")
            return _ctrl(_T_UINT16, len(raw)) + raw
        if value < 1 << 32:
            raw = value.to_bytes((value.bit_length() + 7) // 8, "big")
            return _ctrl(_T_UINT32, len(raw)) + raw
        raw = value.to_bytes((value.bit_length() + 7) // 8, "big")
        return _ctrl(_T_UINT64, len(raw)) + raw
    if isinstance(value, dict):
        out = _ctrl(_T_MAP, len(value))
        for k, v in value.items():
            out += encode_value(str(k)) + encode_value(v)
        return out
    if isinstance(value, (list, tuple)):
        out = _ctrl(_T_ARRAY, len(value))
        for item in value:
            out += encode_value(item)
        return out
    raise TypeError(f"unsupported fixture value type: {type(value)!r}")


class MMDBWriter:
    """Build a .mmdb byte blob from disjoint (network -> record) entries.

    IPv4 networks in an ip_version-6 database land under ``::/96`` —
    exactly where :class:`logparser_tpu.geoip.mmdb.MMDBReader` (and
    MaxMind's own readers) walk 96 zero bits to find them.
    """

    def __init__(self, database_type: str, ip_version: int = 6,
                 description: str = "logparser_tpu generated test data"):
        if ip_version not in (4, 6):
            raise ValueError("ip_version must be 4 or 6")
        self.database_type = database_type
        self.ip_version = ip_version
        self.description = description
        self._entries: List[Tuple[int, int, Any]] = []  # (net, plen, data)

    def insert(self, cidr: str, data: Dict[str, Any]) -> None:
        net = ipaddress.ip_network(cidr, strict=True)
        bits = 128 if self.ip_version == 6 else 32
        native_bits = 128 if net.version == 6 else 32
        # Keep only the PREFIX bits (shift the host bits out) — the trie
        # consumes exactly plen bits from the most significant end.
        prefix = int(net.network_address) >> (native_bits - net.prefixlen)
        plen = net.prefixlen
        if net.version == 4 and self.ip_version == 6:
            plen += 96  # map into ::/96 (the leading bits are zero)
        elif net.version == 6 and self.ip_version == 4:
            raise ValueError("cannot insert IPv6 into an IPv4 database")
        if plen > bits:
            raise ValueError(cidr)
        self._entries.append((prefix, plen, data))

    def to_bytes(self) -> bytes:
        # ---- trie ------------------------------------------------------
        EMPTY = -1
        nodes: List[List[Any]] = [[EMPTY, EMPTY]]  # child index | ("data", i)

        for idx, (prefix, plen, _) in enumerate(self._entries):
            node = 0
            for depth in range(plen):
                bit = (prefix >> (plen - 1 - depth)) & 1
                child = nodes[node][bit]
                if depth == plen - 1:
                    if child != EMPTY:
                        raise ValueError(
                            "overlapping fixture networks are not supported"
                        )
                    nodes[node][bit] = ("data", idx)
                else:
                    if child == EMPTY:
                        nodes.append([EMPTY, EMPTY])
                        child = len(nodes) - 1
                        nodes[node][bit] = child
                    elif isinstance(child, tuple):
                        raise ValueError(
                            "overlapping fixture networks are not supported"
                        )
                    node = child  # always an int index here

        node_count = len(nodes)

        # ---- data section (whole-record dedup) -------------------------
        data_blob = b""
        offsets: Dict[int, int] = {}       # entry index -> offset
        by_payload: Dict[bytes, int] = {}  # encoded record -> offset
        for idx, (_, _, data) in enumerate(self._entries):
            payload = encode_value(data)
            at = by_payload.get(payload)
            if at is None:
                at = len(data_blob)
                by_payload[payload] = at
                data_blob += payload
            offsets[idx] = at

        # ---- serialize nodes (record_size 24) --------------------------
        def record_value(child: Any) -> int:
            if child == EMPTY:
                return node_count            # "no data" sentinel
            if isinstance(child, tuple):
                return node_count + 16 + offsets[child[1]]
            return child

        tree = bytearray()
        for left, right in nodes:
            lv, rv = record_value(left), record_value(right)
            if max(lv, rv) >= 1 << 24:
                raise ValueError("fixture database too large for 24-bit records")
            tree += lv.to_bytes(3, "big") + rv.to_bytes(3, "big")

        metadata = {
            "binary_format_major_version": 2,
            "binary_format_minor_version": 0,
            "build_epoch": 1700000000,
            "database_type": self.database_type,
            "description": {"en": self.description},
            "ip_version": self.ip_version,
            "languages": ["en"],
            "node_count": node_count,
            "record_size": 24,
        }
        return (
            bytes(tree)
            + b"\x00" * 16
            + data_blob
            + _METADATA_MARKER
            + encode_value(metadata)
        )

    def write(self, path: str) -> str:
        # Atomic: a concurrent reader (bench + pytest racing to generate
        # the shared fixtures) must never see a half-written file.
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(self.to_bytes())
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# Fixture records: the Basjes test ranges the suite (and bench) assert on.
# ---------------------------------------------------------------------------


def _names(en: str) -> Dict[str, Any]:
    return {"names": {"en": en}}


_CITY_RECORD = {
    "city": {**_names("Amstelveen"), "confidence": 1, "geoname_id": 1234},
    "continent": {**_names("Europe"), "code": "EU", "geoname_id": 6255148},
    "country": {
        **_names("Netherlands"), "iso_code": "NL", "geoname_id": 2750405,
        "confidence": 42, "is_in_european_union": True,
    },
    "location": {
        "accuracy_radius": 4, "latitude": 52.5, "longitude": 5.75,
        "metro_code": 5, "average_income": 6, "population_density": 7,
        "time_zone": "Europe/Amsterdam",
    },
    "postal": {"code": "1187", "confidence": 2},
    "subdivisions": [
        {**_names("Noord Holland"), "iso_code": "NH", "confidence": 3},
    ],
}

_COUNTRY_RECORD = {
    "continent": _CITY_RECORD["continent"],
    "country": _CITY_RECORD["country"],
}

_ASN_RECORD_V4 = {
    "autonomous_system_number": 4444,
    "autonomous_system_organization": "Basjes Global Network",
}
_ASN_RECORD_V6 = {
    "autonomous_system_number": 6666,
    "autonomous_system_organization": "Basjes Global Network IPv6",
}
_ISP_RECORD = {
    "autonomous_system_number": 4444,
    "autonomous_system_organization": "Basjes Global Network",
    "isp": "Basjes ISP",
    "organization": "Niels Basjes",
}

V4_TEST_NET = "80.100.47.0/24"
V6_TEST_NET = "2001:980::/29"

_DATABASES = {
    "GeoIP2-City-Test.mmdb": ("GeoIP2-City", [(V4_TEST_NET, _CITY_RECORD)]),
    "GeoIP2-Country-Test.mmdb": (
        "GeoIP2-Country", [(V4_TEST_NET, _COUNTRY_RECORD)]
    ),
    "GeoLite2-ASN-Test.mmdb": (
        "GeoLite2-ASN",
        [(V4_TEST_NET, _ASN_RECORD_V4), (V6_TEST_NET, _ASN_RECORD_V6)],
    ),
    "GeoIP2-ISP-Test.mmdb": ("GeoIP2-ISP", [(V4_TEST_NET, _ISP_RECORD)]),
}


def write_test_databases(directory: str) -> Dict[str, str]:
    """Write all four fixture databases into ``directory``; returns
    {filename: path}."""
    os.makedirs(directory, exist_ok=True)
    out = {}
    for filename, (db_type, entries) in _DATABASES.items():
        writer = MMDBWriter(db_type)
        for cidr, record in entries:
            writer.insert(cidr, record)
        out[filename] = writer.write(os.path.join(directory, filename))
    return out


def _fixture_stamp() -> str:
    """Content hash of the fixture definitions: editing a record
    regenerates stale caches instead of silently serving old data."""
    import hashlib

    return hashlib.sha256(repr(sorted(
        (name, db_type, repr(entries))
        for name, (db_type, entries) in _DATABASES.items()
    )).encode()).hexdigest()[:16]


def ensure_test_databases(directory: Optional[str] = None) -> str:
    """Idempotently materialize the fixtures; returns the directory.

    Default location: ``<repo>/.geoip-fixtures`` (gitignored, tiny)."""
    if directory is None:
        directory = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            ".geoip-fixtures",
        )
    stamp_path = os.path.join(directory, ".stamp")
    stamp = _fixture_stamp()
    stale = not all(
        os.path.exists(os.path.join(directory, name)) for name in _DATABASES
    )
    if not stale:
        try:
            with open(stamp_path) as f:
                stale = f.read().strip() != stamp
        except OSError:
            stale = True
    if stale:
        write_test_databases(directory)
        tmp = f"{stamp_path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(stamp)
        os.replace(tmp, stamp_path)
    return directory


def main() -> None:  # pragma: no cover - CLI convenience
    import argparse

    ap = argparse.ArgumentParser(
        description="Generate self-contained GeoIP test databases (.mmdb)"
    )
    ap.add_argument("directory", nargs="?", default=None)
    args = ap.parse_args()
    where = ensure_test_databases(args.directory)
    print(where)


if __name__ == "__main__":  # pragma: no cover
    main()
