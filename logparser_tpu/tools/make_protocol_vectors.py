"""Generate the frozen wire-protocol conformance vectors (docs/PROTOCOL.md).

Writes the request byte streams under tests/golden/protocol/ and, with
``--expected``, computes 01_expected.json by replaying 01 against a live
ParseService.  The .bin files are FROZEN protocol v1 artifacts: regenerate
only to add NEW vectors, never to change existing bytes.
"""
from __future__ import annotations

import json
import os
import struct

GOLDEN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tests", "golden", "protocol",
)

CONFIG = {
    "log_format": "combined",
    "fields": [
        "IP:connection.client.host",
        "HTTP.QUERYSTRING:request.firstline.uri.query",
        "BYTES:response.body.bytes",
        "STRING:request.firstline.uri.query.*",
    ],
    "timestamp_format": None,
}

LINES = [
    b'1.2.3.4 - - [25/Oct/2015:04:11:25 +0100] "GET /a?x=1&y=%4A HTTP/1.1" '
    b'200 1234 "http://r.example/" "ua"',
    b'5.6.7.8 - - [25/Oct/2015:04:11:26 +0100] "GET /b HTTP/1.1" 304 - '
    b'"-" "ua2"',
    b'9.9.9.9 - - [25/Oct/2015:04:11:27 +0100] "GET /c? HTTP/1.1" 200 7 '
    b'"-" "ua3"',
    b"complete garbage that matches no format",
]


def frame(payload: bytes) -> bytes:
    return struct.pack(">I", len(payload)) + payload


def lines_frame(lines) -> bytes:
    return frame(struct.pack(">I", len(lines)) + b"\n".join(lines))


def build_01() -> bytes:
    return (
        frame(json.dumps(CONFIG).encode("utf-8"))
        + lines_frame(LINES)
        + frame(struct.pack(">I", 0))  # count=0: empty batch
        + struct.pack(">I", 0)  # end of session
    )


def build_02() -> bytes:
    bad = {"log_format": "%{unterminated", "fields": ["IP:connection.client.host"]}
    return (
        frame(json.dumps(bad).encode("utf-8"))
        + lines_frame(LINES[:1])
        + struct.pack(">I", 0)
    )


def build_03() -> bytes:
    good_cfg = frame(json.dumps(CONFIG).encode("utf-8"))
    # count header says 3 but payload has 1 line -> per-request error.
    broken = frame(struct.pack(">I", 3) + LINES[0])
    return (
        good_cfg + broken + lines_frame(LINES[:1]) + struct.pack(">I", 0)
    )


def write_vectors() -> None:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    for name, blob in (
        ("01_session_request.bin", build_01()),
        ("02_bad_config_request.bin", build_02()),
        ("03_bad_lines_request.bin", build_03()),
    ):
        path = os.path.join(GOLDEN_DIR, name)
        if os.path.exists(path):
            with open(path, "rb") as f:
                if f.read() != blob:
                    raise SystemExit(
                        f"{name} exists with DIFFERENT bytes — protocol "
                        "vectors are frozen; add a new vector instead"
                    )
            continue
        with open(path, "wb") as f:
            f.write(blob)
        print("wrote", path)


def write_expected() -> None:
    from logparser_tpu.service import ParseService, read_frame

    import pyarrow as pa
    import socket

    with ParseService() as svc:
        with socket.create_connection((svc.host, svc.port)) as sock:
            with open(os.path.join(GOLDEN_DIR, "01_session_request.bin"),
                      "rb") as f:
                sock.sendall(f.read())
            batches = []
            for _ in range(2):
                payload = read_frame(sock)
                with pa.ipc.open_stream(pa.BufferReader(payload)) as r:
                    table = r.read_all()
                batches.append({
                    col: table[col].to_pylist() for col in table.column_names
                })
    out = os.path.join(GOLDEN_DIR, "01_expected.json")
    with open(out, "w") as f:
        # Map-column rows arrive as lists of (key, value) tuples;
        # default=list turns them into JSON [key, value] pairs.
        json.dump({"batches": batches}, f, indent=1, default=list)
    print("wrote", out)


if __name__ == "__main__":
    import sys

    write_vectors()
    if "--expected" in sys.argv:
        write_expected()
