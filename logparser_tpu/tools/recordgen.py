"""Record-class generator: print a ready-made annotated record for a format.

Reference behavior: utils/PojoGenerator/.../PojoGenerator.java:31-60 — build a
parser for the logformat, add every possible path as a target, then print one
annotated setter per (path, cast).  Here the output is a Python record class
using the ``@field`` decorator, with the cast expressed as the value
parameter's type annotation (str/int/float — the signature-dispatch analogue
of Parser.java:590-603).

CLI:  python -m logparser_tpu.tools.recordgen --logformat 'combined'
"""
from __future__ import annotations

import argparse
import re
import sys
from typing import List, Optional, Sequence

from ..core.casts import Cast

_CAST_TO_PYTYPE = {Cast.STRING: "str", Cast.LONG: "int", Cast.DOUBLE: "float"}
# Deterministic output order (Java EnumSet iterates in declaration order).
_CAST_ORDER = [Cast.STRING, Cast.LONG, Cast.DOUBLE]


def _method_name(path: str) -> str:
    name = path.split(":", 1)[1]
    return "set_" + re.sub(r"[^0-9a-zA-Z]+", "_", name).strip("_").lower()


def generate_record_class(
    log_format: str,
    class_name: str = "MyRecord",
    fields: Optional[Sequence[str]] = None,
) -> str:
    """Source text of an annotated record class covering every possible path
    (or the given subset)."""
    from ..adapters.inputformat import build_metadata_parser

    parser = build_metadata_parser(log_format)
    paths = list(fields) if fields else parser.get_possible_paths()
    parser.add_parse_target("set_value", list(paths))
    parser.assemble_dissectors()

    lines: List[str] = [
        "from logparser_tpu.core.fields import field",
        "",
        "",
        f"class {class_name}:",
    ]
    seen_methods = set()
    for path in paths:
        casts = parser.get_casts(path)
        if not casts:
            continue
        for cast in _CAST_ORDER:
            if cast not in casts:
                continue
            method = _method_name(path)
            pytype = _CAST_TO_PYTYPE[cast]
            if pytype != "str":
                method += f"_{pytype}"
            if method in seen_methods:
                continue
            seen_methods.add(method)
            wildcard = path.endswith(".*")
            args = (
                f"self, name: str, value: {pytype}" if wildcard
                else f"self, value: {pytype}"
            )
            value_expr = '{name!r} = {value!r}' if wildcard else '{value!r}'
            lines.append(f"    @field({path!r})")
            lines.append(f"    def {method}({args}):")
            lines.append(
                f"        print(f'SETTER CALLED FOR {path}: {value_expr}')"
            )
            lines.append("")
    if not seen_methods:
        lines.append("    pass")
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="recordgen",
        description="Generate an annotated record class for a LogFormat",
    )
    ap.add_argument(
        "--logformat", required=True, help="Apache HTTPD / NGINX LogFormat"
    )
    ap.add_argument("--class-name", default="MyRecord")
    ap.add_argument(
        "--fields",
        nargs="*",
        help="optional subset of TYPE:path fields (default: all possible)",
    )
    args = ap.parse_args(argv)
    sys.stdout.write(
        generate_record_class(args.logformat, args.class_name, args.fields)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
