"""CI chaos smoke: the fault matrix must recover to byte parity.

Runs the supervised feeder fabric (2 REAL process workers where
multiprocessing works, threads otherwise) over a demolog corpus with
every fault class injected on purpose (``tools/chaos.py``), across both
process transports (zero-copy ring + pickled escape hatch), and fails
(exit 1) unless:

- every faulted run COMPLETES (no FeederError) and its concatenated
  batch payloads are byte-identical to the corpus — replayed shards,
  re-framed ring batches and quarantined shards included;
- the recovery ledger moved the way the fault demands: worker restarts
  for kills/stalls, exactly one quarantined shard for the poison drill,
  a counted generation mismatch / descriptor fault for the corrupt-
  descriptor drills, a transport demotion for the ring-fault storm;
- the new metric families land in the registry and the rendered
  Prometheus exposition stays structurally valid
  (:func:`logparser_tpu.tools.metrics_smoke.validate_exposition`);
- NO shared-memory segment outlives pool teardown (``/dev/shm`` carries
  no ``lpring_*`` entries afterwards) — recovery must not leak arenas,
  including the ones it rebuilds mid-run.

Usage::

    make chaos-smoke
    python -m logparser_tpu.tools.chaos_smoke
"""
from __future__ import annotations

import os
import sys

N_LINES = 4096
BATCH_LINES = 256
WORKERS = 2
LINE_LEN = 256
SHM_DIR = "/dev/shm"


def _ring_segments():
    from logparser_tpu.feeder import RING_NAME_PREFIX

    if not os.path.isdir(SHM_DIR):
        return None
    return sorted(
        f for f in os.listdir(SHM_DIR) if f.startswith(RING_NAME_PREFIX)
    )


def _io_writer_drill(failures) -> None:
    """Exercise ``io_error``/``enospc`` against the durable job writer
    (jax-free: tables only, no parser)."""
    import tempfile

    from logparser_tpu.feeder.shards import Shard
    from logparser_tpu.jobs.writer import (
        JobWriter,
        ShardWriteError,
        build_reject_table,
        leaked_temp_files,
    )
    from logparser_tpu.tools.chaos import ChaosSpec, WriterChaos

    shard = Shard(0, 0, 0, 64)
    rejects = [(0, 0, 3, "oracle_reject", b"bad line")]
    with tempfile.TemporaryDirectory() as d:
        # Transient: one injected EIO, absorbed by the retry ladder.
        w = JobWriter(d, retries=2, backoff_base_s=0.005,
                      chaos=WriterChaos(ChaosSpec.parse(
                          "io_error:op=fsync:count=1")))
        rec = w.write_shard(shard, build_reject_table(rejects), rejects,
                            lines=8, payload_bytes=64)
        if rec.rejects != 1 or not rec.data_file:
            failures.append("io drill: transient io_error did not commit")
        # Sticky: every retry fails -> ShardWriteError, no tmp debris.
        w = JobWriter(d, retries=1, backoff_base_s=0.005,
                      chaos=WriterChaos(ChaosSpec.parse(
                          "enospc:shard=0:sticky=1")))
        try:
            w.write_shard(shard, build_reject_table(rejects), rejects,
                          lines=8, payload_bytes=64)
            failures.append("io drill: sticky enospc did not fail")
        except ShardWriteError:
            pass
        if leaked_temp_files(d):
            failures.append("io drill: tmp debris leaked after faults")
    print("chaos-smoke: io-fault writer drill OK "
          "(transient retried, sticky failed cleanly)")


def main() -> int:
    from logparser_tpu.feeder import (
        FeederPool,
        SupervisorPolicy,
        ring_available,
    )
    from logparser_tpu.observability import metrics
    from logparser_tpu.tools.demolog import generate_combined_lines
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    lines = generate_combined_lines(N_LINES, seed=29, garbage_fraction=0.01)
    blob = "\n".join(lines).encode()
    reg = metrics()
    policy = SupervisorPolicy(backoff_base_s=0.01,
                              ring_fault_threshold=2)

    # (fault spec, ring-transport only,
    #  {registry counter or stats key: min value/delta})
    drills = [
        ("kill_worker:worker=1:after=2:mode=hard", False,
         {"feeder_worker_restarts_total": 1}),
        ("kill_worker:worker=0:after=0:mode=soft", False,
         {"feeder_worker_restarts_total": 1,
          "feeder_shards_requeued_total": 1}),
        ("drop_done:worker=1", False,
         {"feeder_worker_restarts_total": 1}),
        ("poison_shard:shard=1:mode=hard", False,
         {"feeder_shards_quarantined_total": 1,
          "stats:shards_quarantined": 1}),
        ("corrupt_descriptor:worker=0:index=1:field=generation", True,
         {"feeder_ring_generation_mismatch_total": 1,
          "stats:batches_reframed": 1}),
        ("corrupt_descriptor:worker=0:index=1:field=slot;"
         "corrupt_descriptor:worker=0:index=3:field=slot", True,
         {"feeder_ring_descriptor_faults_total": 2,
          "stats:transport_demotions": 1}),
        ("slot_overflow:worker=1:count=20", True,
         {"feeder_ring_pickle_fallback_total": 1}),
    ]

    failures = []
    segments_before = _ring_segments()
    transports = ("ring", "pickle") if ring_available() else ("pickle",)
    shard_bytes = max(1, len(blob) // 5)
    for transport in transports:
        for spec, ring_only, expected in drills:
            if ring_only and transport != "ring":
                continue
            tag = f"transport={transport} fault={spec.split(':', 1)[0]}"
            before = {name: reg.get(name) for name in expected
                      if not name.startswith("stats:")}
            pool = FeederPool(
                [blob], workers=WORKERS, shard_bytes=shard_bytes,
                batch_lines=BATCH_LINES, line_len=LINE_LEN,
                transport=transport, chaos=spec, policy=policy,
            )
            try:
                ebs = list(pool.batches())
            except Exception as e:  # noqa: BLE001 — a recovery bug, report it
                failures.append(f"{tag}: run ABORTED ({type(e).__name__}: "
                                f"{e})")
                continue
            if b"".join(bytes(e.payload) for e in ebs) != blob:
                failures.append(
                    f"{tag}: recovered payload diverges from the corpus"
                )
            stats = pool.stats()
            for name, floor in expected.items():
                if name.startswith("stats:"):
                    moved = stats.get(name.split(":", 1)[1], 0)
                else:
                    moved = reg.get(name) - before[name]
                if moved < floor:
                    failures.append(
                        f"{tag}: {name} moved {moved} "
                        f"(expected >= {floor})"
                    )
            print(f"chaos-smoke: {tag} mode={stats['mode']} "
                  f"batches={stats['batches']} "
                  f"restarts={stats['worker_restarts']} "
                  f"quarantined={stats['shards_quarantined']} "
                  f"demotions={stats['transport_demotions']} OK")

    # I/O fault primitives (round 13): the durable-job writer must
    # absorb a transient io_error via its retry ladder and fail cleanly
    # (ShardWriteError, tmp cleaned up) on a sticky enospc — the same
    # primitives the job tests and docs/JOBS.md drills use.
    try:
        import pyarrow  # noqa: F401 — writer drill needs Arrow

        _io_writer_drill(failures)
    except ImportError:  # pragma: no cover - arrow ships in CI
        print("chaos-smoke: pyarrow unavailable; io-fault drill skipped")

    # Shared-memory hygiene: recovery rebuilds arenas mid-run — every
    # one of them (original and replacement) must be unlinked by pool
    # teardown.
    segments_after = _ring_segments()
    if segments_before is not None and segments_after is not None:
        leaked = sorted(set(segments_after) - set(segments_before))
        if leaked:
            failures.append(f"leaked shared-memory segments: {leaked}")

    text = reg.prometheus_text()
    for needle in ("logparser_tpu_feeder_worker_restarts_total",
                   "logparser_tpu_feeder_shards_quarantined_total",
                   "logparser_tpu_feeder_shards_requeued_total"):
        if needle not in text:
            failures.append(f"/metrics exposition missing: {needle}")
    failures.extend(validate_exposition(text))

    if failures:
        print("CHAOS SMOKE FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print(f"chaos-smoke OK: {len(drills)} fault drills x "
          f"{len(transports)} transports at {WORKERS} workers — every "
          "run recovered to byte parity, ledger counters moved, no "
          "leaked shm segments")
    return 0


if __name__ == "__main__":
    sys.exit(main())
