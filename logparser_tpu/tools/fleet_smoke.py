"""CI fleet smoke: sidecar hard-kill failover + live rolling restart
under loadgen traffic (docs/SERVICE.md "Fleet" acceptance drill).

Boots a :class:`~logparser_tpu.front.FrontTier` over THREE real sidecar
processes (``python -m logparser_tpu.service --sidecar``), warms the
drill formats on every sidecar, then asserts:

1. **Byte parity** — a session served THROUGH the front returns ARROW
   payloads byte-identical to the same frames served by a solo sidecar
   directly (the front is a pure relay; affinity routing must be
   wire-invisible).
2. **1-of-3 hard kill under load** — ``tools/loadgen.py`` (skewed
   ``--tenants`` identities riding the CONFIG frames) drives the front
   while the sidecar OWNING the hottest key is SIGKILLed mid-window:
   zero TCP resets and zero unstructured sheds (in-flight sessions on
   the dead sidecar get structured ``BUSY{"reason":"sidecar_failover"}``
   frames; retrying clients land on live sidecars), goodput keeps
   flowing, ``front_failovers_total`` moves, and the supervisor
   respawns the dead slot.
3. **Zero-downtime rolling restart** — a second loadgen window triggers
   :meth:`FrontTier.roll` mid-run (the loadgen ``--roll`` hook): every
   sidecar is drained + replaced one at a time while the rest absorb
   its keys; the window must end with zero resets AND zero error
   frames (busy sheds are allowed — they are the structured contract),
   the roll must complete, and every slot's generation must advance.
4. **Fleet exposition** — the front's merged ``/metrics`` is
   structurally valid (`metrics_smoke.validate_exposition`), carries
   the ``front_*`` families, and labels sidecar series with
   ``sidecar="sc<i>"``.

Usage::

    make fleet-smoke
    python -m logparser_tpu.tools.fleet_smoke
"""
from __future__ import annotations

import json
import os
import socket
import struct
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

DRILL_FIELDS = ["IP:connection.client.host", "STRING:request.status.last"]


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _session_payloads(host: str, port: int, config: bytes,
                      payloads: List[bytes]) -> List[Tuple[str, bytes]]:
    sock = socket.create_connection((host, port))
    try:
        sock.settimeout(120)
        _send_frame(sock, config)
        got: List[Tuple[str, bytes]] = []
        for payload in payloads:
            _send_frame(sock, payload)
            header = _recv_exact(sock, 4)
            if header is None:
                got.append(("reset", b""))
                continue
            (n,) = struct.unpack(">I", header)
            if n == 0xFFFFFFFF:
                (m,) = struct.unpack(">I", _recv_exact(sock, 4) or b"\0" * 4)
                got.append(("error", _recv_exact(sock, m) or b""))
            else:
                got.append(("arrow", _recv_exact(sock, n) or b""))
        sock.sendall(struct.pack(">I", 0))
        return got
    finally:
        sock.close()


def _scrape(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode("utf-8")


def _family_total(text: str, family: str) -> float:
    import re

    pat = re.compile(
        r"^" + re.escape(family) + r"(?:\{[^}]*\})? (\S+)$", re.M)
    return sum(float(v) for v in pat.findall(text))


def main() -> int:
    # Fleet supervision smoke, not a perf run: never acquire a TPU, and
    # make sure every spawned sidecar inherits the same platform.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from logparser_tpu.front import FrontPolicy, FrontTier, key_label
    from logparser_tpu.service import ParseServiceClient, _ParserCache
    from logparser_tpu.tools.loadgen import make_lines, run_loadgen
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    problems: List[str] = []
    t_all = time.monotonic()
    policy = FrontPolicy(
        heartbeat_interval_s=0.25,
        # Generous on the shared CI box: a sidecar mid-parse can starve
        # its HTTP thread for seconds without being wedged.
        heartbeat_deadline_s=15.0,
        backoff_base_s=0.1,
        busy_retry_after_s=0.05,
        drain_timeout_s=8.0,
    )
    lines = make_lines("combined", 64, seed=11)
    common_lines = make_lines("common", 64, seed=11)

    def warmup(handle) -> None:
        # Both drill formats compile BEFORE a sidecar joins (or
        # rejoins) the rotation: a cold XLA compile inside a drill
        # window would measure the compiler, and any sidecar may absorb
        # a key after the kill / during the roll.
        with ParseServiceClient(handle.host, handle.port, "combined",
                                DRILL_FIELDS, timeout=120.0) as warm:
            warm.parse(lines)
        with ParseServiceClient(
            handle.host, handle.port, '%h %l %u %t "%r" %>s %b',
            ["IP:connection.client.host", "BYTES:response.body.bytes"],
            timeout=120.0,
        ) as warm:
            warm.parse(common_lines)

    with FrontTier(
        n_sidecars=3,
        metrics_port=0,
        policy=policy,
        sidecar_args=["--drain-deadline", "5", "--max-sessions", "32"],
        warmup_fn=warmup,
    ) as front:
        print(f"fleet-smoke: 3 sidecars up + warm "
              f"({time.monotonic() - t_all:.0f}s)")

        # 1) Byte parity: via the front vs a solo sidecar directly.
        config = json.dumps({
            "log_format": "combined", "fields": DRILL_FIELDS,
            "timestamp_format": None,
        }).encode()
        payloads = [
            struct.pack(">I", n) + "\n".join(lines[:n]).encode()
            for n in (1, 17, 64)
        ]
        _sc_name, sc_host, sc_port, _mp = front.sidecars()[0]
        solo = _session_payloads(sc_host, sc_port, config, payloads)
        fronted = _session_payloads(front.host, front.port, config,
                                    payloads)
        for i, (ref, got) in enumerate(zip(solo, fronted)):
            if got[0] != "arrow":
                problems.append(f"parity round {i}: {got[0]} via front")
            elif got[1] != ref[1]:
                problems.append(
                    f"parity round {i}: front bytes differ from solo "
                    "sidecar"
                )

        metrics_url = f"http://{front.host}:{front.metrics_port}/metrics"
        before = _scrape(metrics_url)

        # 2) 1-of-3 hard kill mid-window, aimed at the sidecar OWNING
        # the combined key (so live sessions are guaranteed on it).
        key = _ParserCache.key_of(json.loads(config))
        order = front.router.order(key_label(key), front._slots)
        victim = order[0]
        victim_pid = victim.handle.pid

        def hard_kill() -> None:
            print(f"fleet-smoke: SIGKILL sidecar {victim.name} "
                  f"(pid {victim_pid})")
            victim.handle.kill()

        record = run_loadgen(
            front.host, front.port, clients=6, duration_s=8.0,
            batch_lines=64, burst=2, interval_s=0.05, tenants=3,
            mid_run_fn=hard_kill, mid_run_at_s=3.0,
        )
        if record["resets"]:
            problems.append(
                f"{record['resets']} connection resets across the "
                "1-of-3 kill drill (every failover must be a "
                "structured BUSY frame)"
            )
        if record["busy_unstructured"]:
            problems.append(
                f"{record['busy_unstructured']} unparseable BUSY frames "
                "during the kill drill"
            )
        if record["ok"] == 0:
            problems.append("no request succeeded during the kill drill")
        if not record.get("mid_run", {}).get("completed"):
            problems.append("the kill trigger never fired")
        after = _scrape(metrics_url)
        failovers = (_family_total(after, "logparser_tpu_front_failovers_total")
                     - _family_total(before,
                                     "logparser_tpu_front_failovers_total"))
        if failovers < 1:
            problems.append(
                "front_failovers_total never moved across a hard kill "
                "with sessions in flight"
            )
        # The supervisor must respawn the dead slot (cold jax boot).
        end = time.monotonic() + 90.0
        while time.monotonic() < end:
            if all(s.ready and s.handle is not None and s.handle.alive()
                   for s in front._slots):
                break
            time.sleep(0.25)
        else:
            problems.append("the killed sidecar was never respawned")
        if front.supervisor.total_restarts < 1:
            problems.append("supervisor recorded no executed respawn")
        print(f"fleet-smoke: kill drill done — ok={record['ok']} "
              f"busy={record['busy']} ({record['busy_reasons']}) "
              f"resets={record['resets']} failovers={failovers:.0f}")

        # 3) Live rolling restart under load: zero failed requests.
        gens = [s.generation for s in front._slots]
        record2 = run_loadgen(
            front.host, front.port, clients=4, duration_s=10.0,
            batch_lines=64, burst=2, interval_s=0.05, tenants=3,
            mid_run_fn=lambda: front.roll(drain_timeout_s=6.0),
            mid_run_at_s=2.0,
        )
        if record2["resets"]:
            problems.append(
                f"{record2['resets']} resets during the rolling restart"
            )
        if record2["errors"]:
            problems.append(
                f"{record2['errors']} error frames during the rolling "
                "restart (zero failed requests required)"
            )
        if record2["ok"] == 0:
            problems.append("no request succeeded during the roll")
        if not record2.get("mid_run", {}).get("completed"):
            problems.append(
                "the rolling restart never completed: "
                f"{record2.get('mid_run')}"
            )
        rolled = [s.generation for s in front._slots]
        if not all(b > a for a, b in zip(gens, rolled)):
            problems.append(
                f"roll did not advance every sidecar generation "
                f"({gens} -> {rolled})"
            )
        print(f"fleet-smoke: roll done — ok={record2['ok']} "
              f"busy={record2['busy']} ({record2['busy_reasons']}) "
              f"errors={record2['errors']} resets={record2['resets']} "
              f"generations {gens} -> {rolled}")

        # 4) Merged fleet exposition.
        text = _scrape(metrics_url)
        problems.extend(validate_exposition(text))
        for needle in (
            "logparser_tpu_front_sessions_routed_total",
            "logparser_tpu_front_failovers_total",
            "logparser_tpu_front_restarts_total",
            'sidecar="sc0"',
        ):
            if needle not in text:
                problems.append(f"fleet exposition missing: {needle}")

    if problems:
        print(f"fleet-smoke: FAIL ({len(problems)} problems)")
        for p in problems:
            print(" -", p)
        return 1
    print(
        "fleet-smoke: OK — front byte-identical to solo sidecar; "
        "1-of-3 SIGKILL absorbed with structured failovers + respawn; "
        "rolling restart under load with zero failed requests; merged "
        f"fleet exposition valid ({time.monotonic() - t_all:.0f}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
