"""CI trace smoke: one CONNECTED distributed trace across a real fleet
plus a crash-safe flight dump naming an injected device fault
(docs/OBSERVABILITY.md "Tracing" / "Flight recorder" acceptance drill).

Boots a :class:`~logparser_tpu.front.FrontTier` over TWO real sidecar
processes with head sampling forced on (``LOGPARSER_TPU_TRACE_SAMPLE=1``
— sidecars inherit the env), a widened coalesce window, and
``oom_batch`` device chaos armed, then asserts:

1. **Connected cross-process trace** — two CONCURRENT sessions through
   the front on the same parser key produce, in the merged front
   ``/tracez`` payload: a ``front_session`` root span per session (the
   front re-serializes CONFIG with ``traceparent`` ONLY for sampled
   sessions), a ``service_request`` child span in the sidecar whose
   ``parent_span_id`` is the front root's span id (the relay carried
   the context across the process boundary), ONE shared
   ``coalesce_batch`` span carrying span-LINKS to BOTH sessions'
   request contexts (N-session fan-in is links, not a fake parent), and
   at least one pipeline-stage child span under the batch span reusing
   the ``PIPELINE_STAGES`` vocabulary.
2. **Flight dump names the injected fault** — the ``oom_batch`` chaos
   fired inside a sidecar and was absorbed silently
   (``_absorb_device_fault``); ``SIGUSR2`` to that sidecar must produce
   ``flight-<pid>.json`` in ``LOGPARSER_TPU_FLIGHT_DIR`` whose event
   ring contains the ``device_fault`` event with ``fault="oom"`` — the
   recovery left no trace on the wire, so the dump is the only
   per-incident record.  The merged front ``/flightz`` must show the
   same event live.

Usage::

    make trace-smoke
    python -m logparser_tpu.tools.trace_smoke
"""
from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time
import urllib.request
from typing import Any, Dict, List

DRILL_FIELDS = ["IP:connection.client.host", "STRING:request.status.last"]


def _scrape_json(url: str) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _all_spans(tracez: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Front + every sidecar's spans from the merged /tracez payload."""
    spans = list((tracez.get("front") or {}).get("spans") or [])
    for payload in (tracez.get("sidecars") or {}).values():
        if isinstance(payload, dict):
            spans.extend(payload.get("spans") or [])
    return spans


def main() -> int:
    # Observability smoke, not a perf run: never acquire a TPU, and make
    # sure every spawned fleet member inherits the same platform AND the
    # tracing/chaos env (ProcessSidecar children inherit os.environ).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flight_dir = tempfile.mkdtemp(prefix="logparser-flight-")
    os.environ["LOGPARSER_TPU_TRACE_SAMPLE"] = "1"
    os.environ["LOGPARSER_TPU_FLIGHT_DIR"] = flight_dir
    # One absorbed device OOM per sidecar (fires on the first device
    # execution, i.e. during warmup) — the flight recorder's feed.
    os.environ["LOGPARSER_TPU_CHAOS"] = "oom_batch:count=1"

    from logparser_tpu.front import FrontPolicy, FrontTier
    from logparser_tpu.service import ParseServiceClient
    from logparser_tpu.tools.loadgen import make_lines

    problems: List[str] = []
    t_all = time.monotonic()
    lines = make_lines("combined", 64, seed=11)
    policy = FrontPolicy(
        heartbeat_interval_s=0.25,
        heartbeat_deadline_s=15.0,
        backoff_base_s=0.1,
        busy_retry_after_s=0.05,
        drain_timeout_s=8.0,
    )

    def warmup(handle: Any) -> None:
        # Compiles the drill key AND consumes the one-shot oom chaos, so
        # the traced sessions below run on a warm, fault-free parser.
        with ParseServiceClient(handle.host, handle.port, "combined",
                                DRILL_FIELDS, timeout=120.0) as warm:
            warm.parse(lines)

    with FrontTier(
        n_sidecars=2,
        metrics_port=0,
        policy=policy,
        sidecar_args=["--drain-deadline", "5", "--max-sessions", "32",
                      # Widen the straggler window so two barrier-
                      # synchronized sessions reliably share one batch.
                      "--coalesce-window-ms", "150"],
        warmup_fn=warmup,
    ) as front:
        print(f"trace-smoke: 2 sidecars up + warm "
              f"({time.monotonic() - t_all:.0f}s)")
        tracez_url = f"http://{front.host}:{front.metrics_port}/tracez"
        flightz_url = f"http://{front.host}:{front.metrics_port}/flightz"

        # 1) Two concurrent sessions, SAME key (affinity routes both to
        # one sidecar), parse through the same coalesce window.
        shared: List[Dict[str, Any]] = []
        spans: List[Dict[str, Any]] = []
        for attempt in range(5):
            barrier = threading.Barrier(2)
            errors: List[str] = []

            def _session() -> None:
                try:
                    with ParseServiceClient(
                        front.host, front.port, "combined", DRILL_FIELDS,
                        timeout=120.0, busy_retries=4,
                    ) as client:
                        barrier.wait(timeout=30)
                        client.parse(lines)
                except Exception as e:  # noqa: BLE001 - smoke reporter
                    errors.append(f"{type(e).__name__}: {e}")

            threads = [threading.Thread(target=_session, daemon=True)
                       for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            if errors:
                problems.append(
                    f"traced session failed (attempt {attempt}): {errors}")
                break
            # Spans land in the buffer at .end(); the front root ends on
            # session exit — give the handler threads a beat.
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not shared:
                spans = _all_spans(_scrape_json(tracez_url))
                shared = [
                    s for s in spans
                    if s["name"] == "coalesce_batch"
                    and len(s.get("links") or []) >= 2
                ]
                if not shared:
                    time.sleep(0.2)
            if shared:
                break
        else:
            problems.append(
                "no coalesce_batch span with >=2 session links after 5 "
                "attempts (shared-batch fan-in never traced)"
            )

        if shared:
            batch = shared[0]
            by_id = {s["span_id"]: s for s in spans}
            linked_ids = {ln["span_id"] for ln in batch["links"]}
            requests = [
                s for s in spans
                if s["name"] == "service_request"
                and s["span_id"] in linked_ids
            ]
            if len(requests) < 2:
                problems.append(
                    f"batch links {sorted(linked_ids)} resolve to only "
                    f"{len(requests)} service_request spans (need 2)"
                )
            roots = []
            for req in requests:
                parent = by_id.get(req.get("parent_span_id") or "")
                if (parent is None or parent["name"] != "front_session"
                        or parent["trace_id"] != req["trace_id"]):
                    problems.append(
                        f"service_request {req['span_id']} does not "
                        "parent under a same-trace front_session root "
                        "(the relay lost the context)"
                    )
                else:
                    roots.append(parent)
            if batch.get("parent_span_id") not in linked_ids:
                problems.append(
                    "coalesce_batch parent is not one of its linked "
                    "request contexts (head session must parent the "
                    "shared batch)"
                )
            if int(batch.get("attrs", {}).get("sessions", 0)) < 2:
                problems.append(
                    f"coalesce_batch attrs claim "
                    f"{batch.get('attrs', {}).get('sessions')} sessions "
                    "(need >=2)"
                )
            stages = [
                s for s in spans
                if s.get("parent_span_id") == batch["span_id"]
                and s["trace_id"] == batch["trace_id"]
            ]
            if not stages:
                problems.append(
                    "no pipeline-stage child spans under the shared "
                    "batch span (stage sink never fired)"
                )
            if not problems:
                print(
                    "trace-smoke: connected trace OK — "
                    f"{len(roots)} front roots -> "
                    f"{len(requests)} service requests -> 1 shared "
                    f"batch ({batch['attrs']['sessions']} sessions, "
                    f"{len(batch['links'])} links) -> "
                    f"{len(stages)} stage spans "
                    f"({sorted({s['name'] for s in stages})})"
                )

        # 2) Flight recorder: the warmup's absorbed oom must be in the
        # live merged /flightz AND in the SIGUSR2 crash dump.
        flightz = _scrape_json(flightz_url)
        live_faults = [
            e
            for payload in (flightz.get("sidecars") or {}).values()
            if isinstance(payload, dict)
            for e in (payload.get("events") or [])
            if e.get("kind") == "device_fault"
        ]
        if not live_faults:
            problems.append(
                "merged /flightz shows no device_fault event although "
                "oom chaos was armed in every sidecar"
            )
        victim = front._slots[0]
        victim_pid = victim.handle.pid
        os.kill(victim_pid, signal.SIGUSR2)
        dump_path = os.path.join(flight_dir, f"flight-{victim_pid}.json")
        end = time.monotonic() + 10.0
        dump = None
        while time.monotonic() < end:
            if os.path.exists(dump_path):
                try:
                    with open(dump_path, encoding="utf-8") as fh:
                        dump = json.load(fh)
                    break
                except ValueError:
                    pass  # racing the atomic replace; retry
            time.sleep(0.1)
        if dump is None:
            problems.append(
                f"SIGUSR2 produced no readable flight dump at {dump_path}")
        else:
            faults = [e for e in dump.get("events", [])
                      if e.get("kind") == "device_fault"]
            if not faults:
                problems.append(
                    "flight dump has no device_fault event "
                    f"(kinds: {sorted({e.get('kind') for e in dump.get('events', [])})})"
                )
            elif faults[0].get("fault") != "oom":
                problems.append(
                    "flight dump device_fault does not name the "
                    f"injected oom: {faults[0]}"
                )
            else:
                print(
                    "trace-smoke: flight dump OK — "
                    f"{dump_path} names the absorbed device fault "
                    f"(fault={faults[0]['fault']}, "
                    f"{len(dump.get('events', []))} events, "
                    f"reason={dump.get('dump_reason')})"
                )

    if problems:
        print(f"trace-smoke: FAIL ({len(problems)} problems)")
        for p in problems:
            print(" -", p)
        return 1
    print(
        "trace-smoke: OK — one connected trace across front, sidecar, "
        "and shared device batch; SIGUSR2 flight dump names the "
        f"injected device fault ({time.monotonic() - t_all:.0f}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
