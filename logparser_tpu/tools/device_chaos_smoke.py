"""CI device smoke: the device-tier fault drills (docs/FAULTS.md).

The device was the last unsupervised single point of failure (every
process tier already drills its kills in CI); this smoke produces each
device-fault class ON PURPOSE via the chaos grammar and fails (exit 1)
unless the fault layer recovers with BYTE-IDENTICAL output and zero
aborts:

- ``oom_batch`` — an injected RESOURCE_EXHAUSTED mid-stream must bisect
  and retry (``device_oom_retries_total`` moves), and the SAME parser
  instance must keep serving ``parse_batch``/``parse_blob``/
  ``parse_encoded`` byte-identically afterwards (no poisoned state);
- sticky ``oom_batch`` — repeated OOMs must permanently clamp the max
  executed bucket (``device_bucket_clamped`` gauge) so later batches
  pre-split BEFORE any device_put (no further injections fire);
- ``wedge_device`` + an armed execution deadline — a wedged execution
  must expire on the abandonable worker and reroute the batch to the
  batched oracle host path, never hang the stream;
- ``fail_compile`` — a jit compile failure must demote the parser key
  to the host oracle (warn-once + ``device_compile_failures_total``)
  and the demoted parser must keep answering exactly;
- the pre-allocation byte budget must answer a structured
  ``DeviceBudgetError`` BEFORE any device_put (never an XLA OOM);
- the jobs CLI must honor SIGTERM (the cloud-TPU preemption notice) at
  a shard commit boundary: exit code 3 (resumable), resume re-parses
  ZERO committed shards, merged output byte-identical to a single-shot
  run — the clean-preemption twin of job_smoke's SIGKILL drill;
- the ``device_*`` metric families land in the registry and the
  rendered Prometheus exposition stays structurally valid.

Usage::

    make device-smoke
    python -m logparser_tpu.tools.device_chaos_smoke
"""
from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

N_LINES = 6000
BATCH = 1024
FMT = "%h %u %>s"
FIELDS = ["IP:connection.client.host", "STRING:request.status.last"]

# SIGTERM drill geometry (the job_smoke shape, smaller; the fast poll
# keeps the signal landing mid-run even when commits burst — the
# job_smoke corpus-sizing note applies here too).
JOB_LINES = 16000
JOB_SHARD_BYTES = 48 << 10
JOB_BATCH_LINES = 1024
TERM_POLL_S = 0.05
TERM_TIMEOUT_S = 300.0


def _lines(n):
    return [
        b"10.0.%d.%d u%d %d" % ((i >> 8) % 256, i % 256, i, 200 + i % 7)
        for i in range(n)
    ]


def _batches(lines):
    return [lines[i: i + BATCH] for i in range(0, len(lines), BATCH)]


def _stream_digest(parser, batches) -> str:
    """Content hash over every batch's copy-mode Arrow IPC bytes — the
    consumer-visible output the parity gates compare."""
    from logparser_tpu.tpu.arrow_bridge import batch_to_arrow, table_to_ipc_bytes

    h = hashlib.blake2b()
    for result in parser.parse_batch_stream(batches, emit_views=False):
        h.update(table_to_ipc_bytes(batch_to_arrow(result, strings="copy")))
    return h.hexdigest()


def _counter(name: str) -> float:
    from logparser_tpu.observability import counter_sum

    return counter_sum(name)


def _job_corpus(path: str) -> None:
    with open(path, "w") as f:
        for i in range(JOB_LINES):
            f.write(f"10.0.{(i >> 8) % 256}.{i % 256} u{i} "
                    f"{200 + i % 7}\n")


def _committed(out_dir: str) -> int:
    from logparser_tpu.jobs.manifest import count_committed_shards

    return count_committed_shards(out_dir)


def _sigterm_drill(tmp: str, failures: list) -> None:
    """SIGTERM the live jobs CLI mid-run: exit 3, resume re-parses zero
    committed shards, merged output byte-identical to single-shot."""
    from logparser_tpu.jobs import (
        EXIT_PREEMPTED,
        JobManifest,
        JobSpec,
        merged_hash,
        run_job,
    )

    corpus = os.path.join(tmp, "job-corpus.log")
    _job_corpus(corpus)

    def spec(name):
        return JobSpec([corpus], FMT, FIELDS, os.path.join(tmp, name),
                       shard_bytes=JOB_SHARD_BYTES,
                       batch_lines=JOB_BATCH_LINES)

    ref = run_job(spec("term-ref"))
    if not ref.complete:
        failures.append(f"sigterm drill: reference run incomplete: "
                        f"{ref.as_dict()}")
        return
    ref_hash = merged_hash(spec("term-ref").out_dir,
                           JobManifest.load(spec("term-ref").out_dir))

    term_dir = spec("termed").out_dir
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else repo_root
    )
    argv = [sys.executable, "-m", "logparser_tpu.jobs", corpus,
            "--format", FMT, "--out", term_dir,
            "--shard-bytes", str(JOB_SHARD_BYTES),
            "--batch-lines", str(JOB_BATCH_LINES)]
    for f in FIELDS:
        argv += ["--field", f]
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, env=env, text=True)
    deadline = time.monotonic() + TERM_TIMEOUT_S
    while time.monotonic() < deadline:
        if _committed(term_dir) >= 2 or proc.poll() is not None:
            break
        time.sleep(TERM_POLL_S)
    if proc.poll() is not None:
        failures.append("sigterm drill: CLI finished before the signal "
                        "landed (shrink JOB_SHARD_BYTES)")
        proc.communicate()
        return
    proc.send_signal(signal.SIGTERM)
    try:
        out, _ = proc.communicate(timeout=TERM_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        failures.append("sigterm drill: CLI never exited after SIGTERM "
                        "(the commit-boundary stop is wedged)")
        return
    if proc.returncode != EXIT_PREEMPTED:
        failures.append(
            f"sigterm drill: exit code {proc.returncode}, expected the "
            f"resumable EXIT_PREEMPTED ({EXIT_PREEMPTED})"
        )
    report = None
    for line in reversed((out or "").splitlines()):
        if line.strip().startswith("{"):
            report = json.loads(line)
            break
    if not report or not report.get("preempted"):
        failures.append(
            f"sigterm drill: CLI report missing preempted flag: {report}"
        )
    committed_at_term = _committed(term_dir)
    if committed_at_term < 1:
        failures.append("sigterm drill: nothing committed before the "
                        "preemption stop")
    resumed = run_job(spec("termed"))
    if not resumed.complete:
        failures.append(f"sigterm drill: resume incomplete: "
                        f"{resumed.as_dict()}")
    if resumed.skipped != committed_at_term:
        failures.append(
            f"sigterm drill: resume re-parsed committed shards "
            f"(skipped {resumed.skipped}, committed at preemption "
            f"{committed_at_term})"
        )
    got = merged_hash(term_dir, JobManifest.load(term_dir))
    if got != ref_hash:
        failures.append("sigterm drill: preempted+resumed output is NOT "
                        "byte-identical to the single-shot run")
    print(f"device-smoke: sigterm drill rc={proc.returncode} "
          f"committed_at_term={committed_at_term} "
          f"skipped_on_resume={resumed.skipped} byte_identical="
          f"{got == ref_hash}")


def main() -> int:
    from logparser_tpu.observability import metrics
    from logparser_tpu.tools.metrics_smoke import validate_exposition
    from logparser_tpu.tpu.batch import TpuBatchParser
    from logparser_tpu.tpu.device_faults import (
        DeviceBudgetError,
        DeviceFaultPolicy,
    )

    failures: list = []
    lines = _lines(N_LINES)
    batches = _batches(lines)
    blob = b"\n".join(lines)

    parser = TpuBatchParser(FMT, FIELDS, device_chaos=None)
    ref_digest = _stream_digest(parser, batches)
    ref_batch = parser.parse_batch(lines[:BATCH]).to_dict()
    ref_blob = parser.parse_blob(blob).to_dict()

    # ---- oom_batch: bisect + retry, same instance keeps serving -------
    p_oom = TpuBatchParser(
        FMT, FIELDS,
        device_chaos=f"oom_batch:count=1:min_lines={BATCH}",
    )
    before = _counter("device_oom_retries_total")
    got = _stream_digest(p_oom, batches)
    if got != ref_digest:
        failures.append("oom drill: faulted stream NOT byte-identical")
    if _counter("device_oom_retries_total") <= before:
        failures.append("oom drill: device_oom_retries_total never moved")
    # Parser-survives-fault: the SAME instance, every ingest surface.
    if p_oom.parse_batch(lines[:BATCH]).to_dict() != ref_batch:
        failures.append("oom drill: parse_batch diverged after the fault")
    if p_oom.parse_blob(blob).to_dict() != ref_blob:
        failures.append("oom drill: parse_blob diverged after the fault")
    print(f"device-smoke: oom drill ok "
          f"(retries={_counter('device_oom_retries_total'):.0f}, "
          f"state={p_oom.device_fault_stats()['state']})")

    # ---- sticky oom: the bucket clamp engages and injections stop ----
    p_clamp = TpuBatchParser(
        FMT, FIELDS,
        device_chaos=f"oom_batch:sticky=1:min_lines={BATCH // 2 + 1}",
        fault_policy=DeviceFaultPolicy(oom_clamp_after=2),
    )
    if _stream_digest(p_clamp, batches) != ref_digest:
        failures.append("clamp drill: faulted stream NOT byte-identical")
    stats = p_clamp.device_fault_stats()
    if not stats["oom_clamp"] or stats["oom_clamp"] > BATCH // 2:
        failures.append(f"clamp drill: bucket never clamped ({stats})")
    fired_before = p_clamp._device_chaos.fired("oom_batch")
    if _stream_digest(p_clamp, batches) != ref_digest:
        failures.append("clamp drill: post-clamp stream NOT identical")
    if p_clamp._device_chaos.fired("oom_batch") != fired_before:
        failures.append(
            "clamp drill: clamped batches still reached the device "
            "above the clamp (injections kept firing)"
        )
    print(f"device-smoke: clamp drill ok (clamp={stats['oom_clamp']})")

    # ---- wedge_device + deadline: expire and reroute, never hang -----
    p_wedge = TpuBatchParser(
        FMT, FIELDS, execute_deadline_s=0.5,
        device_chaos="wedge_device:seconds=3:count=1",
    )
    before = _counter("device_fault_reroutes_total")
    t0 = time.monotonic()
    if _stream_digest(p_wedge, batches) != ref_digest:
        failures.append("wedge drill: faulted stream NOT byte-identical")
    wall = time.monotonic() - t0
    if _counter("device_fault_reroutes_total") <= before:
        failures.append("wedge drill: no oracle reroute recorded")
    if wall > 60.0:
        failures.append(f"wedge drill: stream took {wall:.0f}s — the "
                        "deadline did not fire")
    if p_wedge.parse_batch(lines[:BATCH]).to_dict() != ref_batch:
        failures.append("wedge drill: parse_batch diverged afterwards")
    print(f"device-smoke: wedge drill ok ({wall:.1f}s)")

    # ---- fail_compile: demote to oracle, keep answering exactly ------
    p_comp = TpuBatchParser(FMT, FIELDS, device_chaos="fail_compile")
    before = _counter("device_compile_failures_total")
    if _stream_digest(p_comp, batches) != ref_digest:
        failures.append("compile drill: faulted stream NOT byte-identical")
    if _counter("device_compile_failures_total") <= before:
        failures.append("compile drill: failure counter never moved")
    if p_comp.device_fault_stats()["state"] != "demoted":
        failures.append("compile drill: parser was not demoted "
                        f"({p_comp.device_fault_stats()})")
    if p_comp.parse_batch(lines[:BATCH]).to_dict() != ref_batch:
        failures.append("compile drill: demoted parse_batch diverged")
    print("device-smoke: compile drill ok (demoted, exact)")

    # ---- budget: structured reject BEFORE device_put -----------------
    p_budget = TpuBatchParser(FMT, FIELDS, device_bytes_budget=256)
    try:
        p_budget.parse_batch(lines[:BATCH])
        failures.append("budget drill: undersized budget never rejected")
    except DeviceBudgetError as e:
        if e.estimated_bytes <= e.budget_bytes:
            failures.append(f"budget drill: nonsense estimate {e}")
    p_roomy = TpuBatchParser(FMT, FIELDS, device_bytes_budget=1 << 30)
    if p_roomy.parse_batch(lines[:BATCH]).to_dict() != ref_batch:
        failures.append("budget drill: roomy budget changed the output")
    print("device-smoke: budget drill ok (structured reject)")

    # ---- parse_encoded survives a fault (feeder-framed surface) ------
    from logparser_tpu.native import encode_blob
    from logparser_tpu.feeder.worker import EncodedBatch

    small = b"\n".join(lines[:BATCH])
    buf, lens, ovf = encode_blob(small)
    eb = EncodedBatch(shard=0, index=0, payload=small, buf=buf,
                      lengths=lens, overflow=list(ovf),
                      n_lines=buf.shape[0])
    p_enc = TpuBatchParser(FMT, FIELDS, device_chaos="oom_batch:count=1")
    if p_enc.parse_encoded(eb).to_dict() != ref_batch:
        failures.append("encoded drill: faulted parse_encoded diverged")
    print("device-smoke: parse_encoded drill ok")

    # ---- SIGTERM preemption (jobs CLI) -------------------------------
    tmp = tempfile.mkdtemp(prefix="logparser-device-smoke-")
    try:
        _sigterm_drill(tmp, failures)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)

    # ---- exposition + family presence --------------------------------
    text = metrics().prometheus_text()
    problems = validate_exposition(text)
    if problems:
        failures.append(f"exposition invalid: {problems[:3]}")
    for family in ("device_faults_total", "device_oom_retries_total",
                   "device_fault_reroutes_total",
                   "device_compile_failures_total",
                   "device_demotions_total", "device_bucket_clamped",
                   "device_budget_rejects_total"):
        if family not in text:
            failures.append(f"metric family {family} missing from "
                            "the exposition")

    parser.close()
    for p in (p_oom, p_clamp, p_wedge, p_comp, p_budget, p_roomy, p_enc):
        p.close()
    if failures:
        print("device-smoke FAILURES:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("device-smoke: all device-fault drills recovered "
          "byte-identically with zero aborts")
    return 0


if __name__ == "__main__":
    sys.exit(main())
