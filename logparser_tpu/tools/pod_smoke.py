"""CI pod smoke: the pod-level kill drill on REAL host subprocesses.

Drills the pod-scale parse fabric (docs/JOBS.md "Pod jobs") end to end
and fails (exit 1) unless:

- a single-host reference job over a garbage-bearing corpus completes
  (the reject channel is live) and records the reference content hash;
- a 2-host pod — each host a REAL subprocess of the per-host CLI
  (``python -m logparser_tpu.jobs --hosts 2 --host-index i``), running
  multi-device data-parallel dissection over a virtual mesh
  (``XLA_FLAGS=--xla_force_host_platform_device_count``) — survives a
  SIGKILL (-9) of one host mid-run: the survivor completes its share,
  the dead host's range is exactly its uncommitted shards, a PARTIAL
  merge is legal, and resuming the lost host + final merge yields a
  merged output (data + reject tables, global shard order)
  BYTE-IDENTICAL to the single-host reference — with the shards
  committed before the kill never re-parsed;
- a full ``run_pod`` pass over the finished directory is a no-op that
  still exercises the pod metric families (``pod_*`` on /metrics);
- no ``*.tmp`` debris and no shared-memory segment survives.

Usage::

    make pod-smoke
    python -m logparser_tpu.tools.pod_smoke
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

N_LINES = 24000
GARBAGE_EVERY = 997          # ~24 reject lines across the corpus
SHARD_BYTES = 48 << 10       # ~25 shards -> ~12 per host: a wide kill window
BATCH_LINES = 1024
KILL_POLL_S = 0.2
KILL_TIMEOUT_S = 300.0
HOST_TIMEOUT_S = 300.0
DATA_PARALLEL = 2            # virtual 2-device mesh per host
SHM_DIR = "/dev/shm"

FMT = "%h %u %>s"
FIELDS = ["IP:connection.client.host", "STRING:request.status.last"]


def _corpus(path: str) -> None:
    with open(path, "w") as f:
        for i in range(N_LINES):
            if i % GARBAGE_EVERY == 7:
                f.write(f"?? broken line {i} !! ::\n")
            else:
                f.write(f"10.0.{(i >> 8) % 256}.{i % 256} u{i} "
                        f"{200 + i % 7}\n")


def _ring_segments():
    from logparser_tpu.feeder import RING_NAME_PREFIX

    if not os.path.isdir(SHM_DIR):
        return None
    return sorted(
        f for f in os.listdir(SHM_DIR) if f.startswith(RING_NAME_PREFIX)
    )


def _committed(out_dir: str, name: str) -> int:
    try:
        with open(os.path.join(out_dir, name), "rb") as f:
            return len(json.loads(f.read().decode()).get("shards", {}))
    except (OSError, ValueError):
        return 0


def main() -> int:
    from logparser_tpu.jobs import (
        JobManifest,
        JobSpec,
        host_manifest_name,
        leaked_temp_files,
        merge_manifests,
        merged_hash,
        run_job,
    )
    from logparser_tpu.observability import metrics
    from logparser_tpu.pod import PodPolicy, PodSpec, run_pod
    from logparser_tpu.pod.runner import host_argv
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    failures = []
    segments_before = _ring_segments()
    tmp = tempfile.mkdtemp(prefix="logparser-pod-smoke-")
    corpus = os.path.join(tmp, "corpus.log")
    _corpus(corpus)

    # ---- single-host reference (in-process, single device) -----------
    ref_spec = JobSpec([corpus], FMT, FIELDS,
                       os.path.join(tmp, "single-host"),
                       shard_bytes=SHARD_BYTES, batch_lines=BATCH_LINES)
    t0 = time.perf_counter()
    ref = run_job(ref_spec)
    ref_wall = time.perf_counter() - t0
    if not ref.complete:
        failures.append(f"reference run incomplete: {ref.as_dict()}")
    if not ref.rejects:
        failures.append("reference run saw no rejects (corpus has "
                        "garbage lines — the reject channel is dark)")
    ref_hash = merged_hash(ref_spec.out_dir,
                           JobManifest.load(ref_spec.out_dir))
    print(f"pod-smoke: reference {ref.shards_total} shards, "
          f"{ref.rows} rows, {ref.rejects} rejects, "
          f"{ref.payload_bytes / max(ref_wall, 1e-9) / 1e6:.1f} MB/s")

    # ---- the pod: 2 real host subprocesses, kill host 1 mid-run ------
    pod_dir = os.path.join(tmp, "pod")
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # The multi-device leg: each host lays its device parse over a
    # forced 2-device CPU mesh (the TPU build box swaps in real chips).
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={DATA_PARALLEL}"
    )
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else repo_root
    )
    spec = PodSpec([corpus], FMT, FIELDS, pod_dir, n_hosts=2,
                   shard_bytes=SHARD_BYTES, batch_lines=BATCH_LINES,
                   data_parallel=DATA_PARALLEL)
    policy = PodPolicy(host_timeout_s=HOST_TIMEOUT_S)
    procs = [
        subprocess.Popen(host_argv(spec, i, policy), env=env,
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL,
                         start_new_session=True)
        for i in (0, 1)
    ]
    victim_manifest = host_manifest_name(1)
    committed_at_kill = 0
    deadline = time.monotonic() + KILL_TIMEOUT_S
    while time.monotonic() < deadline:
        committed_at_kill = _committed(pod_dir, victim_manifest)
        if committed_at_kill >= 1 or procs[1].poll() is not None:
            break
        time.sleep(KILL_POLL_S)
    if procs[1].poll() is None:
        os.kill(procs[1].pid, signal.SIGKILL)
        procs[1].wait(timeout=30)
        print("pod-smoke: SIGKILLed host 1 mid-run")
    else:
        print("pod-smoke: WARNING host 1 finished before the kill "
              "window (fast host) — resume still asserted below")
    try:
        procs[0].wait(timeout=HOST_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        failures.append("host 0 never finished inside its budget")
    if procs[0].returncode != 0:
        failures.append(f"survivor host 0 failed (rc={procs[0].returncode})")
    committed_at_kill = _committed(pod_dir, victim_manifest)
    print(f"pod-smoke: host 1 died with {committed_at_kill} of its "
          f"shards committed; host 0 rc={procs[0].returncode}")

    # A PARTIAL merge mid-loss is legal: the dead host's unfinished
    # range is simply absent from the merged manifest.
    try:
        partial = merge_manifests(pod_dir)
        if len(partial.shards) >= ref.shards_total and \
                procs[1].returncode == -9:
            failures.append("kill drill never landed mid-run")
        print(f"pod-smoke: partial merge holds {len(partial.shards)} of "
              f"{ref.shards_total} shards")
    except Exception as e:  # noqa: BLE001 — a refusal here is a failure
        failures.append(f"partial merge refused: {e}")

    # Orphaned feeder workers of the killed host must self-terminate.
    time.sleep(2.0)

    # ---- resume the lost host (in-process), final merge --------------
    t0 = time.perf_counter()
    revived = run_job(JobSpec(
        [corpus], FMT, FIELDS, pod_dir,
        shard_bytes=SHARD_BYTES, batch_lines=BATCH_LINES,
        n_hosts=2, host_index=1,
    ))
    resume_wall = time.perf_counter() - t0
    if not revived.complete:
        failures.append(f"host 1 resume incomplete: {revived.as_dict()}")
    if revived.skipped != committed_at_kill:
        failures.append(
            f"resume re-parsed committed work: skipped "
            f"{revived.skipped}, manifest had {committed_at_kill} at kill"
        )
    try:
        merged = merge_manifests(pod_dir)
        if len(merged.shards) != ref.shards_total:
            failures.append(
                f"final merge holds {len(merged.shards)} shards, "
                f"expected {ref.shards_total}"
            )
        pod_hash = merged_hash(pod_dir, JobManifest.load(pod_dir))
        if pod_hash != ref_hash:
            failures.append(
                "pod output is NOT byte-identical to the single-host "
                f"reference ({pod_hash[:16]} != {ref_hash[:16]})"
            )
        else:
            print(f"pod-smoke: kill+resume+merge byte-identical "
                  f"({pod_hash[:16]}), resume wall {resume_wall:.2f}s, "
                  f"skipped {revived.skipped} committed shards")
    except Exception as e:  # noqa: BLE001
        failures.append(f"final merge failed: {e}")

    # ---- run_pod no-op pass: pod metric families in THIS process -----
    report = run_pod(spec, policy=PodPolicy(
        host_timeout_s=HOST_TIMEOUT_S,
        host_retries=0))
    if not report.complete:
        failures.append(f"no-op run_pod incomplete: {report.as_dict()}")
    if any(h.report and h.report.get("committed") for h in report.hosts):
        failures.append("no-op run_pod re-parsed committed shards")

    # ---- hygiene ------------------------------------------------------
    for d in (ref_spec.out_dir, pod_dir):
        debris = leaked_temp_files(d)
        if debris:
            failures.append(f"{d}: leaked temp files {debris}")
    segments_after = _ring_segments()
    if segments_before is not None and segments_after is not None:
        leaked = sorted(set(segments_after) - set(segments_before))
        if leaked:
            failures.append(f"leaked shared-memory segments: {leaked}")

    # ---- telemetry ----------------------------------------------------
    text = metrics().prometheus_text()
    for needle in ("logparser_tpu_pod_runs_total",
                   "logparser_tpu_pod_hosts_launched_total",
                   "logparser_tpu_pod_merge_runs_total",
                   "logparser_tpu_job_shards_committed_total"):
        if needle not in text:
            failures.append(f"/metrics exposition missing: {needle}")
    failures.extend(validate_exposition(text))

    if failures:
        print("POD SMOKE FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print("pod-smoke OK: 2-host pod with a mid-run host SIGKILL "
          "resumed + merged byte-identical to single-host, committed "
          "shards never re-parsed, multi-device mesh per host, "
          "pod_* families live, no leaked temp files or shm segments")
    return 0


if __name__ == "__main__":
    sys.exit(main())
