"""CI job smoke: the kill-drill invariant on a REAL SIGKILL.

Drills the durable batch tier (docs/JOBS.md) end to end and fails
(exit 1) unless:

- a single-shot job over a demolog-style corpus completes with every
  shard committed and the garbage lines landing in reject tables;
- a second job, SIGKILLed (-9) mid-run from another process, RESUMES
  from its manifest to a merged output (data + reject tables, global
  shard order) BYTE-IDENTICAL to the single-shot run's — with the
  shards committed before the kill never re-parsed;
- no ``*.tmp`` debris and no shared-memory segment survives either
  run (the feeder's orphan watch must clean up after the kill);
- the ``job_*`` metric families land in the registry and the rendered
  Prometheus exposition stays structurally valid.

Usage::

    make job-smoke
    python -m logparser_tpu.tools.job_smoke
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

# Corpus sizing vs the kill window: on a fast host the whole commit
# loop can burst through in well under a second (startup/jit dominates
# the run), so the corpus must be big enough that commits SPREAD over a
# multi-second window — otherwise the poll sees "all committed" in one
# step and the SIGKILL can only land after the last commit ("kill
# drill never landed mid-run", observed on the round-17 container at
# 20k lines / 0.2 s polls).
N_LINES = 60000
GARBAGE_EVERY = 997          # ~60 reject lines across the corpus
SHARD_BYTES = 64 << 10       # ~20+ shards: a wide mid-run kill window
BATCH_LINES = 1024
KILL_POLL_S = 0.05
KILL_TIMEOUT_S = 300.0
SHM_DIR = "/dev/shm"

FMT = "%h %u %>s"
FIELDS = ["IP:connection.client.host", "STRING:request.status.last"]


def _corpus(path: str) -> None:
    with open(path, "w") as f:
        for i in range(N_LINES):
            if i % GARBAGE_EVERY == 7:
                f.write(f"?? broken line {i} !! ::\n")
            else:
                f.write(f"10.0.{(i >> 8) % 256}.{i % 256} u{i} "
                        f"{200 + i % 7}\n")


def _ring_segments():
    from logparser_tpu.feeder import RING_NAME_PREFIX

    if not os.path.isdir(SHM_DIR):
        return None
    return sorted(
        f for f in os.listdir(SHM_DIR) if f.startswith(RING_NAME_PREFIX)
    )


def _committed(out_dir: str) -> int:
    """Committed-shard count per the on-disk manifest (atomic rewrite:
    a mid-write read is impossible by construction)."""
    from logparser_tpu.jobs.manifest import count_committed_shards

    return count_committed_shards(out_dir)


def main() -> int:
    from logparser_tpu.jobs import (
        JobManifest,
        JobSpec,
        leaked_temp_files,
        merged_hash,
        run_job,
    )
    from logparser_tpu.observability import metrics
    from logparser_tpu.tools.metrics_smoke import validate_exposition

    failures = []
    segments_before = _ring_segments()
    tmp = tempfile.mkdtemp(prefix="logparser-job-smoke-")
    corpus = os.path.join(tmp, "corpus.log")
    _corpus(corpus)

    def spec(out_name):
        return JobSpec([corpus], FMT, FIELDS,
                       os.path.join(tmp, out_name),
                       shard_bytes=SHARD_BYTES, batch_lines=BATCH_LINES)

    # ---- single-shot reference run (in-process) ----------------------
    t0 = time.perf_counter()
    ref = run_job(spec("single-shot"))
    ref_wall = time.perf_counter() - t0
    if not ref.complete:
        failures.append(f"single-shot run incomplete: {ref.as_dict()}")
    if not ref.rejects:
        failures.append("single-shot run saw no rejects (corpus has "
                        "garbage lines — the reject channel is dark)")
    ref_manifest = JobManifest.load(spec("single-shot").out_dir)
    ref_hash = merged_hash(spec("single-shot").out_dir, ref_manifest)
    print(f"job-smoke: single-shot {ref.shards_total} shards, "
          f"{ref.rows} rows, {ref.rejects} rejects, "
          f"{ref.payload_bytes / max(ref_wall, 1e-9) / 1e6:.1f} MB/s")

    # ---- kill drill: SIGKILL the CLI mid-run, then resume ------------
    kill_dir = spec("killed").out_dir
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else repo_root
    )
    argv = [sys.executable, "-m", "logparser_tpu.jobs", corpus,
            "--format", FMT, "--out", kill_dir,
            "--shard-bytes", str(SHARD_BYTES),
            "--batch-lines", str(BATCH_LINES)]
    for f in FIELDS:
        argv += ["--field", f]
    proc = subprocess.Popen(argv, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    committed_at_kill = 0
    deadline = time.monotonic() + KILL_TIMEOUT_S
    while time.monotonic() < deadline:
        committed_at_kill = _committed(kill_dir)
        if committed_at_kill >= 2 or proc.poll() is not None:
            break
        time.sleep(KILL_POLL_S)
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    else:
        print("job-smoke: WARNING subprocess finished before the kill "
              "window (fast host) — resume still asserted below")
    # Re-read AFTER the process is truly dead: a commit can land between
    # the poll sample and SIGKILL delivery, and resume must be compared
    # against the post-kill manifest truth, not the stale sample.
    committed_at_kill = _committed(kill_dir)
    print(f"job-smoke: job stopped with {committed_at_kill} of "
          f"{ref.shards_total} shards committed")
    if committed_at_kill >= ref.shards_total and proc.returncode == -9:
        failures.append("kill drill never landed mid-run")

    # Orphaned feeder workers must self-terminate and unlink arenas.
    time.sleep(2.0)

    t0 = time.perf_counter()
    resumed = run_job(spec("killed"))
    resume_wall = time.perf_counter() - t0
    if not resumed.complete:
        failures.append(f"resume incomplete: {resumed.as_dict()}")
    if resumed.skipped != committed_at_kill:
        failures.append(
            f"resume re-parsed committed work: skipped "
            f"{resumed.skipped}, manifest had {committed_at_kill} at kill"
        )
    kill_manifest = JobManifest.load(kill_dir)
    kill_hash = merged_hash(kill_dir, kill_manifest)
    if kill_hash != ref_hash:
        failures.append(
            "kill-drill output is NOT byte-identical to the single-shot "
            f"run ({kill_hash[:16]} != {ref_hash[:16]})"
        )
    else:
        print(f"job-smoke: kill+resume byte-identical "
              f"({kill_hash[:16]}), resume wall {resume_wall:.2f}s, "
              f"skipped {resumed.skipped} committed shards")

    # ---- hygiene ------------------------------------------------------
    for out_name in ("single-shot", "killed"):
        debris = leaked_temp_files(spec(out_name).out_dir)
        if debris:
            failures.append(f"{out_name}: leaked temp files {debris}")
    segments_after = _ring_segments()
    if segments_before is not None and segments_after is not None:
        leaked = sorted(set(segments_after) - set(segments_before))
        if leaked:
            failures.append(f"leaked shared-memory segments: {leaked}")

    # ---- telemetry ----------------------------------------------------
    text = metrics().prometheus_text()
    for needle in ("logparser_tpu_job_shards_committed_total",
                   "logparser_tpu_job_rejected_lines_total",
                   "logparser_tpu_job_rows_total"):
        if needle not in text:
            failures.append(f"/metrics exposition missing: {needle}")
    failures.extend(validate_exposition(text))

    if failures:
        print("JOB SMOKE FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print("job-smoke OK: single-shot + SIGKILL/resume byte-identical, "
          "committed shards never re-parsed, reject channel populated, "
          "no leaked temp files or shm segments")
    return 0


if __name__ == "__main__":
    sys.exit(main())
