"""Synthetic access-log generator (the demolog equivalent).

The reference ships a 3456-line real ``combined`` access log
(examples/demolog/hackers-access.log) as golden/bench data.  We generate a
deterministic synthetic corpus with the same statistical shape instead:
realistic IPs, increasing timestamps, encoded + messy query strings, CLF null
bytes, quoted user agents, and a configurable fraction of hostile lines.
"""
from __future__ import annotations

import random
from typing import List

_METHODS = ["GET"] * 8 + ["POST", "HEAD"]
_PATHS = [
    "/", "/index.html", "/apache_pb.gif", "/icons/blank.gif",
    "/login.html", "/api/v1/items", "/search", "/images/logo%20big.png",
    "/a/very/deep/path/with/many/segments/page.html",
]
_QUERIES = [
    "", "", "", "?lang=nl&ref=home", "?q=caf%C3%A9", "?id=123&x=",
    "?a=1&b=2&c=3&utm_source=news", "?broken=50%-off", "?empty",
]
_UAS = [
    "Mozilla/5.0 (X11; Linux x86_64; rv:109.0) Gecko/20100101 Firefox/115.0",
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36 (KHTML, like Gecko) Chrome/120.0 Safari/537.36",
    "Mozilla/4.08 [en] (Win98; I ;Nav)",
    "curl/8.0.1",
    "Googlebot/2.1 (+http://www.google.com/bot.html)",
    "-",
]
_REFERERS = [
    "-", "-", "http://www.example.com/start.html",
    "https://www.google.com/search?q=logparser&ie=utf-8",
    "http://localhost/index.php?mies=wim",
]
_MONTHS = ["Jan", "Feb", "Mar", "Apr", "May", "Jun",
           "Jul", "Aug", "Sep", "Oct", "Nov", "Dec"]
_GARBAGE = [
    '"\\x16\\x03\\x01"',
    "GET / HTTP/1.1",
    "completely broken line",
]


def generate_combined_lines(
    n: int,
    seed: int = 42,
    garbage_fraction: float = 0.0,
) -> List[str]:
    rng = random.Random(seed)
    lines: List[str] = []
    epoch_min = 0
    for i in range(n):
        if garbage_fraction > 0 and rng.random() < garbage_fraction:
            lines.append(rng.choice(_GARBAGE))
            continue
        ip = f"{rng.randint(1, 223)}.{rng.randint(0, 255)}.{rng.randint(0, 255)}.{rng.randint(1, 254)}"
        user = "-" if rng.random() < 0.9 else f"user{rng.randint(1, 99)}"
        epoch_min += rng.randint(0, 2)
        day = 1 + (epoch_min // 1440) % 28
        month = _MONTHS[(epoch_min // 40320) % 12]
        hh = (epoch_min // 60) % 24
        mm = epoch_min % 60
        ss = rng.randint(0, 59)
        tz = rng.choice(["+0100", "-0700", "+0000", "+0530"])
        ts = f"{day:02d}/{month}/2026:{hh:02d}:{mm:02d}:{ss:02d} {tz}"
        method = rng.choice(_METHODS)
        uri = rng.choice(_PATHS) + rng.choice(_QUERIES)
        proto = rng.choice(["HTTP/1.1"] * 8 + ["HTTP/1.0", "HTTP/2.0"])
        status = rng.choice(["200"] * 8 + ["404", "302", "500"])
        size = "-" if rng.random() < 0.1 else str(rng.randint(100, 5_000_000))
        referer = rng.choice(_REFERERS)
        ua = rng.choice(_UAS)
        lines.append(
            f'{ip} - {user} [{ts}] "{method} {uri} {proto}" {status} {size} '
            f'"{referer}" "{ua}"'
        )
    return lines


def truncate_to_common(line: str) -> str:
    """Strip the quoted referer/user-agent tail off a combined line,
    yielding a common-format (`%h %l %u %t "%r" %>s %b`) line.  The ONE
    definition of the combined->common derivation — bench.py's
    multiformat corpus and the loadgen's mixed-format drill both use it,
    so their corpora can never silently diverge."""
    try:
        cut = line.rindex(' "', 0, line.rindex(' "'))
        return line[:cut]
    except ValueError:
        return line


def write_demolog(
    path: str, n: int = 3456, seed: int = 42, garbage_fraction: float = 0.0
) -> int:
    lines = generate_combined_lines(n, seed, garbage_fraction)
    with open(path, "w") as f:
        for line in lines:
            f.write(line + "\n")
    return len(lines)


# The benchmark-of-record field set (bench.py and the device profiler
# both import it, so they can never measure different parsers).
HEADLINE_FIELDS = [
    "IP:connection.client.host",
    "STRING:connection.client.user",
    "TIME.EPOCH:request.receive.time.epoch",
    "HTTP.METHOD:request.firstline.method",
    "HTTP.URI:request.firstline.uri",
    "STRING:request.status.last",
    "BYTES:response.body.bytes",
    "HTTP.URI:request.referer",
    "HTTP.USERAGENT:request.user-agent",
]
