"""Telemetry spine: metrics registry, stage tracing, capped logging, banner.

The reference has no profiling beyond slf4j debug logs (SURVEY §5.1) — real
tracing is new work in this rebuild.  What it does have, and what is kept
bit-compatible in spirit here:

- Hadoop counters "Lines read/Good lines/Bad lines"
  (ApacheHttpdLogfileRecordReader.java:118-120) — each record reader keeps its
  own `adapters.inputformat.Counters` (the per-task view) and also feeds the
  process-wide :class:`CounterRegistry` here (the job-aggregate view).
- Capped error logging, 10 lines max (RecordReader :228-267) —
  :class:`CappedLogger`, used by the record reader; :func:`log_warning_once`
  extends the cap to repeating assembly-time warnings (one print per process,
  then counted).
- A startup version banner with build info (HttpdLoglineParser.java:54-94 +
  the Version template) — :func:`version_banner` / :func:`log_version_banner_once`.

New work:

- :class:`MetricsRegistry` — the process-wide metrics registry (labeled
  counters, gauges, bounded-bucket histograms with p50/p99), exposed via
  :func:`metrics`.  Every hot-path stage feeds it through
  :func:`pipeline_stage`/:func:`observe_stage` at BATCH granularity (one
  lock-guarded histogram update per stage per batch — never per line), so
  disabled-consumer overhead is negligible.  ``service.py`` renders it as a
  Prometheus ``/metrics`` endpoint and an optional per-request STATS frame;
  ``bench.py`` consumes the same :meth:`MetricsRegistry.stage_breakdown`
  definitions for its delivery report, so live serving and the bench speak
  identical stage names (docs/OBSERVABILITY.md is the inventory).
- :class:`Tracer` — per-stage wall-time accounting for the batch pipeline
  (encode, device submit, device fetch, column assembly, oracle fallback),
  enabled via :func:`enable_tracing` or LOGPARSER_TPU_TRACE=1.  The stage set
  mirrors the hot-path inventory in SURVEY §3.3.  The tracer additionally
  makes the ``device`` stage block on kernel completion, so its numbers are
  attribution-exact; the always-on registry never blocks the async dispatch.
- ``jax.profiler`` trace annotations: LOGPARSER_TPU_XPROF_STAGES=1 (or
  :func:`enable_stage_annotations`) wraps every :func:`pipeline_stage` span
  in a named ``jax.profiler.TraceAnnotation`` ("lp.<stage>"), so
  ``tools/profile_device.py`` xplane captures carry host scopes that line up
  with the registry's stage names.
- :func:`profile` — wraps ``jax.profiler.trace`` so a whole parse_batch call
  can be captured for xprof/tensorboard when running on real hardware.
"""
from __future__ import annotations

import bisect
import contextlib
import logging
import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

LOG = logging.getLogger(__name__)


def _env_truthy(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "yes")


# ---------------------------------------------------------------------------
# stage tracing
# ---------------------------------------------------------------------------


@dataclass
class StageStats:
    calls: int = 0
    total_s: float = 0.0
    last_s: float = 0.0
    items: int = 0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "calls": self.calls,
            "total_s": round(self.total_s, 6),
            "last_s": round(self.last_s, 6),
            "items": self.items,
        }


class Tracer:
    """Per-stage wall-clock accounting.  Disabled tracers cost one attribute
    check per stage; timing only happens when enabled."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.stages: Dict[str, StageStats] = {}
        # parse_batch runs on concurrent service threads; stats updates are
        # read-modify-write and must not interleave.
        self._lock = threading.Lock()

    def _record(self, name: str, seconds: float, items: int) -> None:
        with self._lock:
            stats = self.stages.setdefault(name, StageStats())
            stats.calls += 1
            stats.total_s += seconds
            stats.last_s = seconds
            stats.items += items

    @contextlib.contextmanager
    def stage(self, name: str, items: int = 0) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._record(name, time.perf_counter() - t0, items)

    def add(self, name: str, seconds: float, items: int = 0) -> None:
        """Manual accounting for spans that don't nest as a with-block."""
        if not self.enabled:
            return
        self._record(name, seconds, items)

    def reset(self) -> None:
        with self._lock:
            self.stages.clear()

    def report(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            snapshot = {name: s.as_dict() for name, s in self.stages.items()}
        return dict(sorted(snapshot.items()))

    def pretty(self) -> str:
        with self._lock:
            stages = {
                name: (s.calls, s.total_s, s.items)
                for name, s in self.stages.items()
            }
        if not stages:
            return "(no stages recorded)"
        width = max(len(n) for n in stages)
        lines = []
        for name, (calls, total_s, items) in sorted(
            stages.items(), key=lambda kv: -kv[1][1]
        ):
            rate = f"  {items / total_s:12.0f} items/s" if items and total_s else ""
            lines.append(
                f"{name:<{width}}  {calls:6d} calls  {total_s * 1000:10.2f} ms{rate}"
            )
        return "\n".join(lines)


_GLOBAL_TRACER = Tracer(enabled=_env_truthy("LOGPARSER_TPU_TRACE"))


def tracer() -> Tracer:
    return _GLOBAL_TRACER


def enable_tracing() -> Tracer:
    _GLOBAL_TRACER.enabled = True
    return _GLOBAL_TRACER


def disable_tracing() -> Tracer:
    _GLOBAL_TRACER.enabled = False
    return _GLOBAL_TRACER


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a JAX profiler trace (xprof/tensorboard readable) around a
    block — the device-side complement of the host Tracer."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


# ---------------------------------------------------------------------------
# metrics registry: counters + gauges + bounded-bucket histograms
# ---------------------------------------------------------------------------

# Wall-time buckets (seconds) sized for batch-stage latencies: sub-ms host
# stages up through multi-second tunneled transfers.  +Inf is implicit.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# Row-count buckets for batch-size histograms (the bench/service batch
# spectrum: record-reader micro-batches up to the 64k headline and beyond).
BATCH_ROWS_BUCKETS: Tuple[float, ...] = (
    64, 256, 1024, 4096, 16384, 65536, 262144,
)

# Labels as a canonical sorted tuple — the registry's internal key part.
LabelsT = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Optional[Dict[str, str]]) -> LabelsT:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_labels(labels: LabelsT, extra: Optional[List[Tuple[str, str]]] = None) -> str:
    pairs = list(labels) + list(extra or ())
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def _series_name(name: str, labels: LabelsT) -> str:
    return name + _format_labels(labels)


_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Sanitize an internal metric name into the Prometheus grammar
    ([a-zA-Z_:][a-zA-Z0-9_:]*): lowercase, runs of other bytes -> '_'."""
    out = _PROM_NAME_RE.sub("_", name.strip().lower())
    if not out or out[0].isdigit():
        out = "_" + out
    return out


class Histogram:
    """Bounded-bucket histogram: fixed upper bounds (+Inf implicit), count,
    sum, observed min/max.  Percentiles interpolate linearly inside the
    bucket that holds the target rank — the min/max tighten the open-ended
    first and last buckets, so p50/p99 stay meaningful even when every
    observation lands in one bucket."""

    __slots__ = ("name", "labels", "buckets", "_counts", "count", "sum",
                 "_min", "_max", "_lock")

    def __init__(self, name: str, labels: LabelsT = (),
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        self.name = name
        self.labels = labels
        self.buckets: Tuple[float, ...] = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.count = 0
        self.sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) by in-bucket interpolation;
        0.0 when nothing was observed."""
        with self._lock:
            return _interp_percentile(
                self.buckets, self._counts, self.count,
                self._min, self._max, q,
            )

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            count, total = self.count, self.sum
            mn, mx = self._min, self._max
        p50 = _interp_percentile(self.buckets, counts, count, mn, mx, 0.5)
        p99 = _interp_percentile(self.buckets, counts, count, mn, mx, 0.99)
        return {
            "count": count,
            "sum": round(total, 6),
            "min": round(mn if count else 0.0, 6),
            "max": round(mx if count else 0.0, 6),
            "p50": round(p50, 6),
            "p99": round(p99, 6),
            "buckets": [
                [b, c] for b, c in zip(list(self.buckets) + ["+Inf"], counts)
            ],
        }


def _interp_percentile(buckets: Tuple[float, ...], counts: Sequence[int],
                       count: int, mn: float, mx: float, q: float) -> float:
    """The single percentile implementation, over an already-consistent
    (buckets, counts, count, min, max) view — callers hold or copied the
    histogram state."""
    if count == 0:
        return 0.0
    target = q * count
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= target:
            lo = buckets[i - 1] if i > 0 else min(mn, buckets[0])
            hi = buckets[i] if i < len(buckets) else mx
            lo = max(lo, mn)
            hi = min(hi, mx)
            if hi <= lo:
                return hi
            return lo + (hi - lo) * ((target - cum) / c)
        cum += c
    return mx  # unreachable unless counts drifted


class CounterRegistry:
    """Process-wide named counters (the Hadoop Counter analogue); adapters
    keep their own per-reader Counters, this aggregates across them."""

    def __init__(self) -> None:
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    def increment(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


class MetricsRegistry:
    """The full metrics registry (CounterRegistry promoted): labeled
    counters, gauges, and bounded-bucket histograms, with a Prometheus text
    renderer and a structured :meth:`snapshot`.

    One instance is the process-wide spine (:func:`metrics`): the batch
    pipeline, the host pool, the Arrow bridge and the sidecar service all
    write into it; ``service.py``'s ``/metrics`` endpoint and STATS frames
    and ``bench.py``'s delivery breakdown all read from it — same metric
    definitions everywhere.  All updates are batch-granularity (hot loops
    never touch it per line) and lock-guarded (service threads are
    concurrent)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, LabelsT], float] = {}
        self._gauges: Dict[Tuple[str, LabelsT], float] = {}
        self._hists: Dict[Tuple[str, LabelsT], Histogram] = {}

    # -- counters (monotonic) -------------------------------------------

    def increment(self, name: str, delta: float = 1,
                  labels: Optional[Dict[str, str]] = None) -> None:
        key = (name, _labels_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + delta

    def get(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._counters.get((name, _labels_key(labels)), 0)

    # -- gauges ----------------------------------------------------------

    def gauge_set(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[(name, _labels_key(labels))] = float(value)

    def gauge_add(self, name: str, delta: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        key = (name, _labels_key(labels))
        with self._lock:
            self._gauges[key] = self._gauges.get(key, 0.0) + delta

    def gauge_get(self, name: str,
                  labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._gauges.get((name, _labels_key(labels)), 0.0)

    # -- histograms ------------------------------------------------------

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        """Get-or-create: bucket bounds are fixed at first creation."""
        key = (name, _labels_key(labels))
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                hist = self._hists[key] = Histogram(
                    name, key[1], buckets or DEFAULT_TIME_BUCKETS
                )
        return hist

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None,
                buckets: Optional[Sequence[float]] = None) -> None:
        self.histogram(name, labels, buckets).observe(value)

    # -- views -----------------------------------------------------------

    def as_dict(self) -> Dict[str, float]:
        """Counters only, formatted names (CounterRegistry-compatible)."""
        with self._lock:
            return {_series_name(n, lb): v for (n, lb), v in self._counters.items()}

    def snapshot(self) -> Dict[str, Any]:
        """Structured registry state: {"counters", "gauges", "histograms"}
        keyed by formatted series name (labels inline)."""
        with self._lock:
            counters = {
                _series_name(n, lb): v for (n, lb), v in self._counters.items()
            }
            gauges = {
                _series_name(n, lb): v for (n, lb), v in self._gauges.items()
            }
            hists = list(self._hists.items())
        return {
            "counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "histograms": {
                _series_name(n, lb): h.as_dict()
                for (n, lb), h in sorted(hists, key=lambda kv: kv[0])
            },
        }

    def stage_breakdown(self) -> Dict[str, Dict[str, Any]]:
        """Per-pipeline-stage summary from the ``stage_seconds`` histograms
        (+ the ``stage_items_total`` counters): the SINGLE definition the
        /metrics endpoint, the STATS frame and bench.py's delivery section
        all derive from — same stage names everywhere."""
        with self._lock:
            hists = [
                (lb, h) for (n, lb), h in self._hists.items()
                if n == "stage_seconds"
            ]
            items = {
                lb: v for (n, lb), v in self._counters.items()
                if n == "stage_items_total"
            }
        out: Dict[str, Dict[str, Any]] = {}
        for lb, h in hists:
            stage = dict(lb).get("stage", "?")
            d = h.as_dict()
            entry = {
                "calls": d["count"],
                "total_s": d["sum"],
                "p50_ms": round(d["p50"] * 1000.0, 3),
                "p99_ms": round(d["p99"] * 1000.0, 3),
            }
            n_items = items.get(lb, 0)
            if n_items:
                entry["items"] = int(n_items)
                if d["sum"] > 0:
                    entry["items_per_sec"] = round(n_items / d["sum"], 1)
            out[stage] = entry
        return dict(sorted(out.items()))

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    # -- Prometheus text exposition (version 0.0.4) ----------------------

    def prometheus_text(self, prefix: str = "logparser_tpu_") -> str:
        """Render the registry as Prometheus text exposition.  Counter
        names gain a ``_total`` suffix when missing (exposition
        convention); all names are sanitized into the metric-name
        grammar."""
        # Every exposition identifies its producer's build: value-1 info
        # gauge, refreshed per render so it survives reset() and a fleet
        # merge shows each sidecar's version/jax in one scrape.
        self.gauge_set("build_info", 1.0, labels=build_info())
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items(), key=lambda kv: kv[0])
        lines: List[str] = []

        def emit_family(kind: str, series: List[Tuple[Tuple[str, LabelsT], float]],
                        suffix_total: bool) -> None:
            by_base: Dict[str, List[Tuple[LabelsT, float]]] = {}
            for (name, lb), value in series:
                base = prefix + _prom_name(name)
                if suffix_total and not base.endswith("_total"):
                    base += "_total"
                by_base.setdefault(base, []).append((lb, value))
            for base in sorted(by_base):
                lines.append(f"# TYPE {base} {kind}")
                for lb, value in by_base[base]:
                    lines.append(f"{base}{_format_labels(lb)} {_render_num(value)}")

        emit_family("counter", counters, suffix_total=True)
        emit_family("gauge", gauges, suffix_total=False)

        by_base_h: Dict[str, List[Tuple[LabelsT, Histogram]]] = {}
        for (name, lb), h in hists:
            by_base_h.setdefault(prefix + _prom_name(name), []).append((lb, h))
        for base in sorted(by_base_h):
            lines.append(f"# TYPE {base} histogram")
            for lb, h in by_base_h[base]:
                with h._lock:
                    counts = list(h._counts)
                    count, total = h.count, h.sum
                cum = 0
                for bound, c in zip(list(h.buckets) + [float("inf")], counts):
                    cum += c
                    le = "+Inf" if bound == float("inf") else _render_num(bound)
                    lines.append(
                        f"{base}_bucket{_format_labels(lb, [('le', le)])} {cum}"
                    )
                lines.append(f"{base}_sum{_format_labels(lb)} {_render_num(total)}")
                lines.append(f"{base}_count{_format_labels(lb)} {count}")
        return "\n".join(lines) + "\n"


def _render_num(value: float) -> str:
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(float(value)) if isinstance(value, float) else str(value)


_GLOBAL_COUNTERS = CounterRegistry()
_GLOBAL_METRICS = MetricsRegistry()


def counters() -> CounterRegistry:
    """The Hadoop-style job-aggregate counter trio fed by record readers
    (kept separate from :func:`metrics` so its ``as_dict`` stays exactly
    the reference's three-counter surface)."""
    return _GLOBAL_COUNTERS


def metrics() -> MetricsRegistry:
    """The process-wide telemetry registry (see :class:`MetricsRegistry`)."""
    return _GLOBAL_METRICS


def counter_sum(name: str) -> float:
    """Total of one counter family across all label sets — the drills'
    "did this family move" helper (bench/smoke/tests share it so the
    formatted-series key shape has one consumer-side home)."""
    return sum(
        v for k, v in _GLOBAL_METRICS.as_dict().items()
        if k == name or k.startswith(name + "{")
    )


# ---------------------------------------------------------------------------
# pipeline-stage instrumentation: registry (always) + tracer (when enabled)
# + jax.profiler trace annotation (when enabled)
# ---------------------------------------------------------------------------

# Canonical hot-path stage names (docs/OBSERVABILITY.md): the batch pipeline
# emits exactly these via pipeline_stage/observe_stage; bench.py's delivery
# breakdown and tools/profile_device.py host scopes reuse them verbatim.
PIPELINE_STAGES = (
    "encode",            # [B, L] uint8 packing (native framer / per-line)
    "h2d_stage",         # staged async upload enqueue (stream double-buffer)
    "device",            # fused-executor dispatch (kernel time when tracing)
    "fetch",             # packed D2H of the device verdict rows
    "columns",           # packed rows -> typed numpy columns
    "csr_materialize",   # wildcard CSR segment table -> dicts/spans
    "oracle_fallback",   # host per-line engine over routed lines
    "assembly",          # BatchResult -> pyarrow Table (hostpool fan-out)
    "ipc",               # Arrow IPC stream serialization
    "aggregate",         # analytics pushdown: partial fetch + host fold
)

_ANNOTATE = {"enabled": _env_truthy("LOGPARSER_TPU_XPROF_STAGES")}


def enable_stage_annotations() -> None:
    """Wrap every pipeline stage in a named jax.profiler.TraceAnnotation
    ("lp.<stage>") so xprof/tensorboard host tracks line up with the
    registry's stage names.  Also via LOGPARSER_TPU_XPROF_STAGES=1."""
    _ANNOTATE["enabled"] = True


def disable_stage_annotations() -> None:
    _ANNOTATE["enabled"] = False


def stage_annotations_enabled() -> bool:
    return _ANNOTATE["enabled"]


# Injected by logparser_tpu/tracing.py ONLY while a sampled batch scope
# is active (tracing.batch_scope): turns completed stages into child
# spans of the live shared-batch span.  A plain module-global read keeps
# the disabled hot path at one load+compare — and observability never
# imports tracing (the dependency points one way).
_STAGE_SPAN_SINK: Optional[Callable[[str, float, int], None]] = None


def set_stage_span_sink(
    sink: Optional[Callable[[str, float, int], None]],
) -> None:
    global _STAGE_SPAN_SINK
    _STAGE_SPAN_SINK = sink


def observe_stage(name: str, seconds: float, items: int = 0) -> None:
    """Record one completed stage span: always into the metrics registry
    (stage_seconds histogram + stage_items_total counter), and into the
    global Tracer when tracing is enabled.  Batch granularity only."""
    _GLOBAL_METRICS.observe("stage_seconds", seconds, labels={"stage": name})
    if items:
        _GLOBAL_METRICS.increment(
            "stage_items_total", items, labels={"stage": name}
        )
    if _GLOBAL_TRACER.enabled:
        _GLOBAL_TRACER._record(name, seconds, items)
    sink = _STAGE_SPAN_SINK
    if sink is not None:
        sink(name, seconds, items)


@contextlib.contextmanager
def pipeline_stage(name: str, items: int = 0) -> Iterator[None]:
    """Instrument one hot-path stage at batch granularity: one
    perf_counter pair + one histogram update per batch (a few µs against
    multi-ms batches), plus the optional profiler annotation."""
    ann = None
    if _ANNOTATE["enabled"]:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(f"lp.{name}")
            ann.__enter__()
        except Exception:  # noqa: BLE001 — annotation is best-effort
            ann = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if ann is not None:
            ann.__exit__(None, None, None)
        observe_stage(name, time.perf_counter() - t0, items)


def record_batch_shape(rows: int, padded_rows: int, line_len: int,
                       line_bytes: int) -> None:
    """Batch-shape accounting shared by both ingest paths (list encode and
    blob framing): batch-size histogram + pad-waste counters.  Pad waste =
    1 - encoded_line_bytes_total / buffer_cells_total (row padding to the
    bucket AND per-line right-padding to L both count)."""
    reg = _GLOBAL_METRICS
    reg.increment("parse_batches_total")
    reg.increment("parse_lines_total", rows)
    reg.observe("batch_rows", rows, buckets=BATCH_ROWS_BUCKETS)
    if padded_rows > rows:
        reg.increment("pad_rows_total", padded_rows - rows)
    reg.increment("encoded_line_bytes_total", int(line_bytes))
    reg.increment("buffer_cells_total", int(padded_rows) * int(line_len))


# ---------------------------------------------------------------------------
# capped error logging (RecordReader :228-267 caps at 10 lines)
# ---------------------------------------------------------------------------


class CappedLogger:
    """Log at most ``cap`` errors, then one suppression notice, then count
    silently; ``suppressed`` holds the overflow for end-of-run reporting."""

    def __init__(self, logger: logging.Logger, cap: int = 10):
        self._logger = logger
        self.cap = cap
        self.logged = 0
        self.suppressed = 0

    def error(self, msg: str, *args: Any) -> None:
        if self.logged < self.cap:
            self.logged += 1
            self._logger.error(msg, *args)
            if self.logged == self.cap:
                self._logger.error(
                    "Max number of displayed errors (%d) reached; "
                    "further bad lines are counted but not logged.",
                    self.cap,
                )
        else:
            self.suppressed += 1

    def warning(self, msg: str, *args: Any) -> None:
        """The warning-level twin of :meth:`error` (same cap + notice +
        silent count), for repeating non-fatal messages."""
        if self.logged < self.cap:
            self.logged += 1
            self._logger.warning(msg, *args)
            if self.logged == self.cap:
                self._logger.warning(
                    "Max number of displays (%d) of this warning reached; "
                    "further repeats are counted but not logged.",
                    self.cap,
                )
        else:
            self.suppressed += 1


# Per-message cap-1 warning loggers: a message repeated by every parser
# assembly/worker (e.g. the localized-timestamp support warning that spammed
# the BENCH_r05 tail once per format compile) prints ONCE per process, then
# only counts.  The counts surface through suppressed_warning_counts(), the
# metrics registry, and service.py's periodic stats line.
_WARN_ONCE_LOCK = threading.Lock()
_WARN_ONCE: Dict[str, CappedLogger] = {}


def log_warning_once(logger: logging.Logger, message: str) -> None:
    """Emit ``message`` at WARNING level at most once per process; later
    repeats are counted (suppressed_warning_counts) not printed."""
    with _WARN_ONCE_LOCK:
        capped = _WARN_ONCE.get(message)
        if capped is None:
            capped = _WARN_ONCE[message] = CappedLogger(logger, cap=1)
    capped.warning("%s", message)
    if capped.suppressed:
        _GLOBAL_METRICS.increment("suppressed_warnings_total")


def note_teardown(logger: logging.Logger, counter: str, site: str,
                  detail: str) -> None:
    """Teardown/cleanup failures must never be silent: count them under
    ``counter{site=...}`` and warn once per distinct message.  The
    generic form of the feeder's ``note_teardown_error`` escalation
    idiom (PR 6), shared by the serving tier
    (``service_teardown_errors_total``): a leaked session thread or a
    join that times out, repeated across restarts, is exactly the drip
    a long-lived host needs to see."""
    _GLOBAL_METRICS.increment(counter, labels={"site": site})
    log_warning_once(logger, f"teardown: {site}: {detail}")


def suppressed_warning_counts() -> Dict[str, int]:
    """{message: suppressed repeat count} for every once-logged warning
    that repeated — the end-of-run summary companion of
    :func:`log_warning_once`."""
    with _WARN_ONCE_LOCK:
        return {
            msg: c.suppressed for msg, c in _WARN_ONCE.items() if c.suppressed
        }


def reset_warning_once(message: Optional[str] = None) -> None:
    """Forget once-logged state (tests; ``None`` clears everything)."""
    with _WARN_ONCE_LOCK:
        if message is None:
            _WARN_ONCE.clear()
        else:
            _WARN_ONCE.pop(message, None)


# ---------------------------------------------------------------------------
# version banner (HttpdLoglineParser.java:54-94)
# ---------------------------------------------------------------------------

_BANNER_LOGGED = False


def build_info() -> Dict[str, str]:
    """The banner's raw facts as exposition labels: package version and
    the jax version IF some other component already imported it (same
    no-TPU-acquisition discipline as :func:`version_banner`)."""
    import sys

    from . import __version__

    jax_mod = sys.modules.get("jax")
    return {
        "version": str(__version__),
        "jax": str(getattr(jax_mod, "__version__", "unimported"))
        if jax_mod is not None else "unimported",
    }


def version_banner() -> str:
    import sys

    from . import __version__

    # jax.__version__ is safe (importing jax does not initialize a backend);
    # deliberately NO jax.devices()/default_backend() here — enumerating
    # devices would acquire the TPU from a process that may never use it.
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        jax_line = "JAX not imported yet"
    else:
        jax_line = f"JAX {jax_mod.__version__}"
    content = [
        f"logparser_tpu {__version__} — TPU-native access log parsing",
        jax_line,
    ]
    width = max(len(c) for c in content)
    border = "-" * (width + 2)
    lines = [f"/{border}\\"]
    lines.extend(f"| {c:<{width}} |" for c in content)
    lines.append(f"\\{border}/")
    return "\n".join(lines)


def log_version_banner_once(logger: Optional[logging.Logger] = None) -> None:
    global _BANNER_LOGGED
    if _BANNER_LOGGED:
        return
    log = logger or LOG
    if not log.isEnabledFor(logging.INFO):
        return  # don't build (or mark logged) until someone can see it
    _BANNER_LOGGED = True
    log.info("\n%s", version_banner())
