"""Tracing, counters, capped error logging, and the version banner.

The reference has no profiling beyond slf4j debug logs (SURVEY §5.1) — real
tracing is new work in this rebuild.  What it does have, and what is kept
bit-compatible in spirit here:

- Hadoop counters "Lines read/Good lines/Bad lines"
  (ApacheHttpdLogfileRecordReader.java:118-120) — each record reader keeps its
  own `adapters.inputformat.Counters` (the per-task view) and also feeds the
  process-wide :class:`CounterRegistry` here (the job-aggregate view).
- Capped error logging, 10 lines max (RecordReader :228-267) —
  :class:`CappedLogger`, used by the record reader.
- A startup version banner with build info (HttpdLoglineParser.java:54-94 +
  the Version template) — :func:`version_banner` / :func:`log_version_banner_once`.

New work:

- :class:`Tracer` — per-stage wall-time accounting for the batch pipeline
  (encode, device submit, device fetch, column assembly, oracle fallback),
  enabled via :func:`enable_tracing` or LOGPARSER_TPU_TRACE=1.  The stage set
  mirrors the hot-path inventory in SURVEY §3.3.
- :func:`profile` — wraps ``jax.profiler.trace`` so a whole parse_batch call
  can be captured for xprof/tensorboard when running on real hardware.
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field as dataclass_field
from typing import Any, Dict, Iterator, Optional

LOG = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# stage tracing
# ---------------------------------------------------------------------------


@dataclass
class StageStats:
    calls: int = 0
    total_s: float = 0.0
    last_s: float = 0.0
    items: int = 0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "calls": self.calls,
            "total_s": round(self.total_s, 6),
            "last_s": round(self.last_s, 6),
            "items": self.items,
        }


class Tracer:
    """Per-stage wall-clock accounting.  Disabled tracers cost one attribute
    check per stage; timing only happens when enabled."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.stages: Dict[str, StageStats] = {}
        # parse_batch runs on concurrent service threads; stats updates are
        # read-modify-write and must not interleave.
        self._lock = threading.Lock()

    def _record(self, name: str, seconds: float, items: int) -> None:
        with self._lock:
            stats = self.stages.setdefault(name, StageStats())
            stats.calls += 1
            stats.total_s += seconds
            stats.last_s = seconds
            stats.items += items

    @contextlib.contextmanager
    def stage(self, name: str, items: int = 0) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._record(name, time.perf_counter() - t0, items)

    def add(self, name: str, seconds: float, items: int = 0) -> None:
        """Manual accounting for spans that don't nest as a with-block."""
        if not self.enabled:
            return
        self._record(name, seconds, items)

    def reset(self) -> None:
        with self._lock:
            self.stages.clear()

    def report(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            snapshot = {name: s.as_dict() for name, s in self.stages.items()}
        return dict(sorted(snapshot.items()))

    def pretty(self) -> str:
        with self._lock:
            stages = {
                name: (s.calls, s.total_s, s.items)
                for name, s in self.stages.items()
            }
        if not stages:
            return "(no stages recorded)"
        width = max(len(n) for n in stages)
        lines = []
        for name, (calls, total_s, items) in sorted(
            stages.items(), key=lambda kv: -kv[1][1]
        ):
            rate = f"  {items / total_s:12.0f} items/s" if items and total_s else ""
            lines.append(
                f"{name:<{width}}  {calls:6d} calls  {total_s * 1000:10.2f} ms{rate}"
            )
        return "\n".join(lines)


_GLOBAL_TRACER = Tracer(
    enabled=os.environ.get("LOGPARSER_TPU_TRACE", "").strip().lower()
    in ("1", "true", "yes")
)


def tracer() -> Tracer:
    return _GLOBAL_TRACER


def enable_tracing() -> Tracer:
    _GLOBAL_TRACER.enabled = True
    return _GLOBAL_TRACER


def disable_tracing() -> Tracer:
    _GLOBAL_TRACER.enabled = False
    return _GLOBAL_TRACER


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a JAX profiler trace (xprof/tensorboard readable) around a
    block — the device-side complement of the host Tracer."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------


class CounterRegistry:
    """Process-wide named counters (the Hadoop Counter analogue); adapters
    keep their own per-reader Counters, this aggregates across them."""

    def __init__(self) -> None:
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    def increment(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


_GLOBAL_COUNTERS = CounterRegistry()


def counters() -> CounterRegistry:
    return _GLOBAL_COUNTERS


# ---------------------------------------------------------------------------
# capped error logging (RecordReader :228-267 caps at 10 lines)
# ---------------------------------------------------------------------------


class CappedLogger:
    """Log at most ``cap`` errors, then one suppression notice, then count
    silently; ``suppressed`` holds the overflow for end-of-run reporting."""

    def __init__(self, logger: logging.Logger, cap: int = 10):
        self._logger = logger
        self.cap = cap
        self.logged = 0
        self.suppressed = 0

    def error(self, msg: str, *args: Any) -> None:
        if self.logged < self.cap:
            self.logged += 1
            self._logger.error(msg, *args)
            if self.logged == self.cap:
                self._logger.error(
                    "Max number of displayed errors (%d) reached; "
                    "further bad lines are counted but not logged.",
                    self.cap,
                )
        else:
            self.suppressed += 1


# ---------------------------------------------------------------------------
# version banner (HttpdLoglineParser.java:54-94)
# ---------------------------------------------------------------------------

_BANNER_LOGGED = False


def version_banner() -> str:
    import sys

    from . import __version__

    # jax.__version__ is safe (importing jax does not initialize a backend);
    # deliberately NO jax.devices()/default_backend() here — enumerating
    # devices would acquire the TPU from a process that may never use it.
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        jax_line = "JAX not imported yet"
    else:
        jax_line = f"JAX {jax_mod.__version__}"
    content = [
        f"logparser_tpu {__version__} — TPU-native access log parsing",
        jax_line,
    ]
    width = max(len(c) for c in content)
    border = "-" * (width + 2)
    lines = [f"/{border}\\"]
    lines.extend(f"| {c:<{width}} |" for c in content)
    lines.append(f"\\{border}/")
    return "\n".join(lines)


def log_version_banner_once(logger: Optional[logging.Logger] = None) -> None:
    global _BANNER_LOGGED
    if _BANNER_LOGGED:
        return
    log = logger or LOG
    if not log.isEnabledFor(logging.INFO):
        return  # don't build (or mark logged) until someone can see it
    _BANNER_LOGGED = True
    log.info("\n%s", version_banner())
