"""Distributed request tracing + crash-safe flight recorder.

The second observability spine beside the metrics registry
(docs/OBSERVABILITY.md "Tracing" / "Flight recorder"):

- **TraceContext** — W3C-traceparent-compatible identity
  (``00-<32hex trace_id>-<16hex span_id>-<2hex flags>``) propagated on
  the wire via the CONFIG ``traceparent`` key (docs/PROTOCOL.md) and
  across process boundaries via the ``LOGPARSER_TPU_TRACEPARENT`` env.
- **Head-based sampling** — ``LOGPARSER_TPU_TRACE_SAMPLE`` (0..1,
  default 0 = off).  The sampling decision is made ONCE at the head of
  a trace (front session admit / job start / loadgen client) and rides
  the context; an unsampled process pays one cached float compare per
  span site and allocates nothing.
- **SpanBuffer** — bounded in-process ring of completed spans, exported
  as JSON at ``GET /tracez`` on the existing metrics endpoint, plus an
  optional JSON-lines span log (``LOGPARSER_TPU_TRACE_LOG``).
- **Flight recorder** — an always-on fixed-size ring of structured
  events fed by every site that recovers *silently* (device-fault
  absorption, feeder supervisor decisions, front failovers, service
  sheds), dumped to ``flight-<pid>.json`` on SIGTERM / SIGUSR2 / fatal
  fault and served at ``GET /flightz`` — the 60-second postmortem that
  survives the process.

Import discipline: this module imports :mod:`.observability` (stdlib
only); observability never imports tracing at module level — the stage
span sink is injected (:func:`observability.set_stage_span_sink`) and
only while a sampled batch scope is active, so the disabled hot path
keeps its exact pre-tracing instruction stream.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import secrets
import sys
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence

from . import observability
from .observability import metrics

__all__ = [
    "TraceContext",
    "Span",
    "parse_traceparent",
    "new_trace_context",
    "sample_rate",
    "set_sample_rate",
    "head_context",
    "root_span",
    "child_span",
    "batch_scope",
    "push_batch_span",
    "pop_batch_span",
    "span_buffer",
    "tracez_payload",
    "flight_event",
    "flightz_payload",
    "dump_flight",
    "flight_dump_path",
    "sweep_flight_dumps",
    "arm_flight_signals",
    "install_flight_excepthook",
    "reset_for_tests",
]

_TRACEPARENT_VERSION = "00"


# ---------------------------------------------------------------------------
# trace context (W3C traceparent)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TraceContext:
    """One position in a trace: which trace, which span, sampled or not.

    Immutable; ``child()`` mints a fresh span identity inside the same
    trace with the same sampling decision (head-based: the flag never
    flips downstream)."""

    trace_id: str  # 32 lowercase hex chars, not all-zero
    span_id: str   # 16 lowercase hex chars, not all-zero
    sampled: bool

    def traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"{_TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}-{flags}"

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _rand_hex(16), self.sampled)


def _rand_hex(n: int) -> str:
    return secrets.token_hex(n // 2)


def parse_traceparent(value: Any) -> Optional[TraceContext]:
    """Decode a ``traceparent`` header/CONFIG value; ``None`` on any
    malformation.  Invalid contexts are silently dropped (the W3C
    contract: a bad traceparent must not break the request)."""
    if not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if version != _TRACEPARENT_VERSION:
        return None
    if len(trace_id) != 32 or len(span_id) != 16 or len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return TraceContext(trace_id, span_id, bool(int(flags, 16) & 0x01))


def new_trace_context(sampled: bool = True) -> TraceContext:
    return TraceContext(_rand_hex(32), _rand_hex(16), sampled)


# ---------------------------------------------------------------------------
# head-based sampling
# ---------------------------------------------------------------------------


def _env_rate() -> float:
    raw = os.environ.get("LOGPARSER_TPU_TRACE_SAMPLE", "").strip()
    if not raw:
        return 0.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 0.0


_SAMPLE_RATE = _env_rate()


def sample_rate() -> float:
    return _SAMPLE_RATE


def set_sample_rate(rate: float) -> None:
    """Programmatic override (bench A/B, tests); env is read once at
    import so sidecars inherit the smoke process's decision."""
    global _SAMPLE_RATE
    _SAMPLE_RATE = min(1.0, max(0.0, float(rate)))


def head_context(traceparent: Any = None) -> Optional[TraceContext]:
    """The one sampling decision point.  An incoming context is
    respected verbatim (sampled or not — the head already decided);
    with none, coin-flip at :func:`sample_rate`.  Returns ``None`` on
    a miss so every downstream span site is a single ``is None``."""
    ctx = parse_traceparent(traceparent)
    if ctx is not None:
        return ctx
    rate = _SAMPLE_RATE
    if rate <= 0.0:
        return None
    if rate < 1.0 and secrets.randbelow(1 << 30) >= int(rate * (1 << 30)):
        return None
    return new_trace_context(sampled=True)


# ---------------------------------------------------------------------------
# spans + bounded buffer
# ---------------------------------------------------------------------------


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


class SpanBuffer:
    """Bounded thread-safe ring of completed spans (dicts).  Overflow
    drops the OLDEST span (recent history is the debugging surface) and
    counts ``trace_spans_dropped_total``."""

    def __init__(self, maxlen: int = 2048):
        self.maxlen = int(maxlen)
        self._spans: deque = deque(maxlen=self.maxlen)
        self._lock = threading.Lock()
        self.dropped = 0

    def record(self, span: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._spans) == self.maxlen:
                self.dropped += 1
                metrics().increment("trace_spans_dropped_total")
            self._spans.append(span)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0


_SPAN_BUFFER = SpanBuffer(_env_int("LOGPARSER_TPU_TRACE_BUFFER", 2048))


def span_buffer() -> SpanBuffer:
    return _SPAN_BUFFER


_SPAN_LOG_LOCK = threading.Lock()
_SPAN_LOG: Dict[str, Any] = {"path": None, "fh": None}


def _span_log_write(record: Dict[str, Any]) -> None:
    path = os.environ.get("LOGPARSER_TPU_TRACE_LOG", "").strip()
    if not path:
        return
    with _SPAN_LOG_LOCK:
        try:
            if _SPAN_LOG["path"] != path:
                if _SPAN_LOG["fh"] is not None:
                    _SPAN_LOG["fh"].close()
                _SPAN_LOG["fh"] = open(path, "a", encoding="utf-8")
                _SPAN_LOG["path"] = path
            _SPAN_LOG["fh"].write(json.dumps(record, sort_keys=True) + "\n")
            _SPAN_LOG["fh"].flush()
        except OSError:
            _SPAN_LOG["path"], _SPAN_LOG["fh"] = None, None


class Span:
    """A live span handle.  ``end()`` is idempotent and records the
    completed span into the process buffer (+ span log + metrics); an
    unsampled site never sees one of these (the factories return
    ``None`` instead, so the hot path is one branch)."""

    __slots__ = ("name", "context", "parent_span_id", "start_s",
                 "attrs", "links", "_ended")

    def __init__(self, name: str, context: TraceContext,
                 parent_span_id: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None,
                 links: Sequence[TraceContext] = ()):
        self.name = name
        self.context = context
        self.parent_span_id = parent_span_id
        self.start_s = time.time()
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.links: List[TraceContext] = list(links)
        self._ended = False

    @property
    def traceparent(self) -> str:
        return self.context.traceparent()

    def add_link(self, ctx: Optional[TraceContext]) -> None:
        if ctx is not None:
            self.links.append(ctx)

    def end(self, **attrs: Any) -> None:
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        end_s = time.time()
        record = {
            "name": self.name,
            "trace_id": self.context.trace_id,
            "span_id": self.context.span_id,
            "parent_span_id": self.parent_span_id,
            "start_s": self.start_s,
            "duration_ms": round((end_s - self.start_s) * 1000.0, 3),
            "attrs": self.attrs,
        }
        if self.links:
            record["links"] = [
                {"trace_id": c.trace_id, "span_id": c.span_id}
                for c in self.links
            ]
        _SPAN_BUFFER.record(record)
        metrics().increment("trace_spans_total", labels={"name": self.name})
        _span_log_write(record)


def root_span(name: str, traceparent: Any = None,
              attrs: Optional[Dict[str, Any]] = None) -> Optional[Span]:
    """Open a span at a trace head: continue an incoming context as its
    child, or head-sample a fresh trace.  ``None`` when unsampled."""
    ctx = head_context(traceparent)
    if ctx is None or not ctx.sampled:
        return None
    incoming = parse_traceparent(traceparent)
    if incoming is not None:
        return Span(name, incoming.child(),
                    parent_span_id=incoming.span_id, attrs=attrs)
    return Span(name, ctx, parent_span_id=None, attrs=attrs)


def child_span(name: str, parent: Optional[TraceContext],
               attrs: Optional[Dict[str, Any]] = None,
               links: Sequence[TraceContext] = ()) -> Optional[Span]:
    """Open a child span under ``parent``'s context; ``None`` when the
    parent is absent or unsampled (zero-cost pass-through)."""
    if parent is None or not parent.sampled:
        return None
    return Span(name, parent.child(),
                parent_span_id=parent.span_id, attrs=attrs, links=links)


# ---------------------------------------------------------------------------
# pipeline-stage child spans (the observe_stage sink)
# ---------------------------------------------------------------------------
#
# tpu/batch.py times its stages through observability.observe_stage; while
# a sampled batch scope is active the sink below turns each completed
# stage into a child span of the innermost batch span, so trace
# vocabulary == scrape vocabulary (PIPELINE_STAGES).  The sink is only
# installed while >=1 scope is live: an unsampled process never even
# loads this module from the hot path.

_BATCH_STACK: List[Span] = []
_BATCH_LOCK = threading.Lock()


def _stage_sink(name: str, seconds: float, items: int) -> None:
    with _BATCH_LOCK:
        parent = _BATCH_STACK[-1] if _BATCH_STACK else None
    if parent is None:
        return
    span = Span(name, parent.context.child(),
                parent_span_id=parent.context.span_id)
    span.start_s = time.time() - seconds
    if items:
        span.end(items=items)
    else:
        span.end()


def push_batch_span(span: Optional[Span]) -> None:
    """Make ``span`` the innermost stage-attribution target.  Explicit
    push/pop (vs only :func:`batch_scope`) because the coalescer's
    streamed formed-batches begin at formation and end after scatter —
    lifetimes that cross generator frames."""
    if span is None:
        return
    with _BATCH_LOCK:
        _BATCH_STACK.append(span)
        if len(_BATCH_STACK) == 1:
            observability.set_stage_span_sink(_stage_sink)


def pop_batch_span(span: Optional[Span]) -> None:
    if span is None:
        return
    with _BATCH_LOCK:
        try:
            _BATCH_STACK.remove(span)
        except ValueError:
            pass
        if not _BATCH_STACK:
            observability.set_stage_span_sink(None)


@contextlib.contextmanager
def batch_scope(span: Optional[Span]) -> Iterator[None]:
    """While active, completed pipeline stages become child spans of
    ``span``.  A ``None`` span is a no-op (unsampled batch)."""
    push_batch_span(span)
    try:
        yield
    finally:
        pop_batch_span(span)


def tracez_payload() -> Dict[str, Any]:
    """The ``GET /tracez`` body: recent completed spans, oldest first."""
    return {
        "pid": os.getpid(),
        "sample_rate": _SAMPLE_RATE,
        "buffer_maxlen": _SPAN_BUFFER.maxlen,
        "dropped": _SPAN_BUFFER.dropped,
        "spans": _SPAN_BUFFER.snapshot(),
    }


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Always-on bounded ring of recent structured events from sites
    that recover silently.  Recording is a dict build + deque append
    under a lock — cheap enough for fault paths (which are off the
    per-line hot path by construction)."""

    def __init__(self, maxlen: int = 256):
        self.maxlen = int(maxlen)
        self._events: deque = deque(maxlen=self.maxlen)
        self._lock = threading.Lock()
        self.total = 0

    def record(self, kind: str, **fields: Any) -> None:
        event = {"t": time.time(), "kind": str(kind)}
        for k, v in fields.items():
            # "t"/"kind" are reserved envelope keys — a payload field
            # must never overwrite the event's identity.
            if v is not None and k not in ("t", "kind"):
                event[k] = v if isinstance(v, (int, float, bool)) else str(v)
        with self._lock:
            self._events.append(event)
            self.total += 1
        metrics().increment("flight_events_total", labels={"kind": str(kind)})

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.total = 0


_FLIGHT = FlightRecorder(_env_int("LOGPARSER_TPU_FLIGHT_EVENTS", 256))


def flight_recorder() -> FlightRecorder:
    return _FLIGHT


def flight_event(kind: str, **fields: Any) -> None:
    """Record one flight-recorder event (module-level convenience; the
    silent-recovery sites call exactly this)."""
    _FLIGHT.record(kind, **fields)


def flightz_payload(reason: Optional[str] = None) -> Dict[str, Any]:
    payload: Dict[str, Any] = {
        "pid": os.getpid(),
        "argv0": sys.argv[0] if sys.argv else "",
        "events_total": _FLIGHT.total,
        "ring_maxlen": _FLIGHT.maxlen,
        "events": _FLIGHT.snapshot(),
    }
    if reason is not None:
        payload["dump_reason"] = reason
    return payload


def flight_dir() -> str:
    """The dump directory: ``$LOGPARSER_TPU_FLIGHT_DIR``, defaulting to
    a per-machine run directory under the system temp root (dumps used
    to land in cwd, which litters whatever directory a CLI happened to
    start in)."""
    base = os.environ.get("LOGPARSER_TPU_FLIGHT_DIR", "").strip()
    return base or os.path.join(tempfile.gettempdir(), "logparser_tpu-flight")


def flight_dump_path(pid: Optional[int] = None) -> str:
    """Where a dump for ``pid`` (default: this process) lands:
    :func:`flight_dir` ``/flight-<pid>.json``."""
    return os.path.join(flight_dir(), f"flight-{pid or os.getpid()}.json")


def dump_flight(reason: str) -> Optional[str]:
    """Write the crash-safe dump; returns the path, or ``None`` if the
    write failed (a dying process must not die harder over telemetry).
    Atomic rename so a reader never sees a torn file."""
    path = flight_dump_path()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(flightz_payload(reason), fh, sort_keys=True)
        os.replace(tmp, path)
        return path
    except OSError:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        return None


_FLIGHT_NAME_RE = re.compile(r"^flight-(\d+)\.json$")

#: Dead-writer dumps retained after a sweep (most-recent first) —
#: post-mortem material for the runs that just crashed, without letting
#: a crash-looping fleet grow the directory without bound.
FLIGHT_KEEP_DEFAULT = 8


def sweep_flight_dumps(directory: Optional[str] = None,
                       keep: Optional[int] = None) -> List[str]:
    """Startup hygiene for the dump directory: unlink ``flight-<pid>.json``
    files whose writer pid is dead (the jobs-tier ``sweepable_temp_files``
    dead-pid rule — a live pid is a concurrent local process, an
    unkillable/unknowable one is left alone), keeping the ``keep``
    most-recently-modified dead dumps (``LOGPARSER_TPU_FLIGHT_KEEP``,
    default :data:`FLIGHT_KEEP_DEFAULT`).  Returns the removed paths."""
    base = directory if directory is not None else flight_dir()
    if keep is None:
        keep = _env_int("LOGPARSER_TPU_FLIGHT_KEEP", FLIGHT_KEEP_DEFAULT)
    try:
        names = os.listdir(base)
    except OSError:
        return []
    dead: List[tuple] = []
    for name in names:
        m = _FLIGHT_NAME_RE.match(name)
        if not m:
            continue
        pid = int(m.group(1))
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            pass            # dead writer: sweepable crash debris
        except OSError:
            continue        # unknowable (e.g. other uid): leave it
        else:
            continue        # alive: a concurrent local process
        path = os.path.join(base, name)
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            continue
        dead.append((mtime, path))
    dead.sort(reverse=True)
    removed = []
    for _, path in dead[max(0, keep):]:
        with contextlib.suppress(OSError):
            os.unlink(path)
            removed.append(path)
    return removed


def arm_flight_signals() -> None:
    """Install the SIGUSR2 dump trigger ("what was this process
    absorbing, without killing it"), chaining any prior handler.
    SIGTERM dumps are wired inside each CLI's existing graceful-drain
    handler (service.py / front.py) — not here — so drain semantics
    stay owned by the server."""
    import signal

    prev = signal.getsignal(signal.SIGUSR2)

    def _on_sigusr2(signum: int, frame: Any) -> None:  # noqa: ARG001
        flight_event("sigusr2_dump")
        dump_flight("sigusr2")
        if callable(prev) and prev not in (
            signal.SIG_IGN, signal.SIG_DFL, _on_sigusr2
        ):
            prev(signum, frame)

    try:
        signal.signal(signal.SIGUSR2, _on_sigusr2)
    except (ValueError, OSError, AttributeError):
        pass  # non-main thread / platform without SIGUSR2


def install_flight_excepthook() -> None:
    """Chain a sys.excepthook that dumps the flight ring on a fatal
    (uncaught) fault before the process dies — the last 60 s of
    silently-absorbed trouble usually explains the crash."""
    prev = sys.excepthook

    def _hook(exc_type: type, exc: BaseException, tb: Any) -> None:
        flight_event("fatal_fault", error=f"{exc_type.__name__}: {exc}")
        dump_flight("fatal_fault")
        prev(exc_type, exc, tb)

    if getattr(prev, "__name__", "") != "_hook":
        sys.excepthook = _hook


# ---------------------------------------------------------------------------
# test support
# ---------------------------------------------------------------------------


def reset_for_tests(sample_rate_value: Optional[float] = None) -> None:
    """Clear span buffer + flight ring and (optionally) re-pin the
    sample rate; re-reads the env when no explicit rate is given."""
    _SPAN_BUFFER.clear()
    _FLIGHT.clear()
    with _BATCH_LOCK:
        _BATCH_STACK.clear()
    observability.set_stage_span_sink(None)
    set_sample_rate(_env_rate() if sample_rate_value is None
                    else sample_rate_value)
