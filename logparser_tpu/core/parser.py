"""The engine: dissector registry, demand-driven graph compiler, host executor.

Reference behavior: parser-core/.../core/Parser.java (1016 LoC).  The compiler
semantics replicated here:

- assembly (Parser.java:237-356): fixpoint over create_additional_dissectors,
  explode dissectors into (input_type, output_type, name) phases, compute all
  possible subtargets from requested paths, recursively find useful dissectors
  from the root, prepare_for_run every compiled instance, verify nothing
  requested is unreachable (MissingDissectorsException unless ignored).
- findUsefulDissectorsFromField (Parser.java:360-458): wildcard ``*`` outputs
  match any requested path under the current prefix; per-node dissector clones
  via get_new_instance; casts recorded from prepare_for_dissect; type remappings
  recursed with STRING_ONLY casts.
- parse (Parser.java:700-756): worklist loop over to-be-parsed fields invoking
  each compiled phase.
- store (Parser.java:760-876): setter dispatch honoring Casts and SetterPolicy;
  2-arg setters receive the full ``TYPE:path`` id as the name argument.
- getPossiblePaths (Parser.java:904-965): recursive path expansion with
  max-depth guard and cycle avoidance, plus type-remapping paths.

The Parser object is picklable (the Java parser is Serializable for shipping
into Hadoop/Flink tasks, Parser.java:91-97): targets are stored as method-name
specs, resolved against the record instance at store time.
"""
from __future__ import annotations

import inspect
import logging
from typing import (
    Any,
    Callable,
    Dict,
    FrozenSet,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
    Union,
)

from .casts import Cast, STRING_ONLY
from .dissector import Dissector
from .exceptions import (
    DissectionFailure,
    FatalErrorDuringCallOfSetterMethod,
    InvalidDissectorException,
    InvalidFieldMethodSignature,
    MissingDissectorsException,
)
from .fields import (
    SetterPolicy,
    cleanup_field_value,
    get_field_paths,
    get_field_policy,
)
from .parsable import Parsable
from .value import Value

LOG = logging.getLogger(__name__)

# Sentinel: the compiled line-program has not been built yet for the current
# assembly (distinct from None = "compiled path unavailable, use generic").
_FASTLINE_UNSET = object()


class _DissectorPhase:
    __slots__ = ("input_type", "output_type", "name", "instance")

    def __init__(self, input_type: str, output_type: str, name: str, instance: Dissector):
        self.input_type = input_type
        self.output_type = output_type
        self.name = name
        self.instance = instance

    def __repr__(self) -> str:
        return f"Phase({self.input_type}:->{self.output_type}:{self.name})"


class _TargetSpec:
    """One registered setter: resolved lazily by name against the record."""

    __slots__ = ("method_name", "arg_count", "value_type", "policy")

    def __init__(self, method_name: str, arg_count: int, value_type: str, policy: SetterPolicy):
        self.method_name = method_name
        self.arg_count = arg_count  # 1 = (value), 2 = (name, value)
        self.value_type = value_type  # "STRING" | "LONG" | "DOUBLE" | "AUTO"
        self.policy = policy

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _TargetSpec) and (
            self.method_name,
            self.arg_count,
            self.value_type,
            self.policy,
        ) == (other.method_name, other.arg_count, other.value_type, other.policy)

    def __hash__(self) -> int:
        return hash((self.method_name, self.arg_count, self.value_type, self.policy))


_TYPE_NAMES = {str: "STRING", int: "LONG", float: "DOUBLE"}


def _inspect_setter(record_class: Optional[type], fn: Callable) -> Tuple[int, str]:
    """Return (arg_count, value_type) for a setter callable/method."""
    sig = inspect.signature(fn)
    params = [p for p in sig.parameters.values() if p.name != "self"]
    if len(params) not in (1, 2):
        raise InvalidFieldMethodSignature(
            f"Setter {getattr(fn, '__qualname__', fn)} must take (value) or "
            f"(name, value); got {len(params)} parameters"
        )
    value_param = params[-1]
    ann = value_param.annotation
    if ann is inspect.Parameter.empty:
        vtype = "AUTO"
    elif ann in _TYPE_NAMES:
        vtype = _TYPE_NAMES[ann]
    elif isinstance(ann, str):
        vtype = {"str": "STRING", "int": "LONG", "float": "DOUBLE"}.get(ann, "AUTO")
    else:
        vtype = "AUTO"
    if len(params) == 2:
        first = params[0].annotation
        if first not in (inspect.Parameter.empty, str, "str"):
            raise InvalidFieldMethodSignature(
                f"Setter {getattr(fn, '__qualname__', fn)}: the name parameter must be str"
            )
    return len(params), vtype


class Parser:
    """Demand-driven dissection engine, generic over the record type.

    ``record_class`` may be any class; methods decorated with
    :func:`logparser_tpu.core.fields.field` become parse targets automatically
    (the reference scans ``@Field`` annotations in its constructor,
    Parser.java:496-507).
    """

    def __init__(self, record_class: Optional[type] = None):
        self.record_class = record_class
        self.all_dissectors: List[Dissector] = []
        self.root_type: Optional[str] = None
        # field id -> set of target specs
        self.targets: Dict[str, Set[_TargetSpec]] = {}
        self.casts_of_targets: Dict[str, FrozenSet[Cast]] = {}
        self.type_remappings: Dict[str, Set[str]] = {}
        self._assembled = False
        self._fail_on_missing_dissectors = True
        self._compiled: Dict[str, List[_DissectorPhase]] = {}
        self._useful_intermediates: Set[str] = set()
        self._located_targets: Set[str] = set()
        self._needed_frozen: Optional[FrozenSet[str]] = None
        self._last_chance: Dict[str, Tuple[str, Any]] = {}
        # Line-invariant add_dissection routing decisions, keyed by
        # (base, type, name); reset whenever the parser (re)assembles.
        self.dissection_memo: Dict[tuple, tuple] = {}
        self._store_plans: Dict[Any, Any] = {}
        # Compiled per-format store programs (core/fastline.py): the parse
        # hot path when the parser shape supports it.  _FASTLINE_UNSET ->
        # compile on first parse; None -> compiled path unavailable, use
        # the generic engine.  use_fastline=False disables it entirely
        # (the differential tests compare both paths).
        self._fastline: Any = _FASTLINE_UNSET
        self.use_fastline = True

        if record_class is not None:
            for name in dir(record_class):
                try:
                    fn = getattr(record_class, name)
                except AttributeError:
                    continue
                paths = get_field_paths(fn)
                if paths is not None:
                    self.add_parse_target(fn, paths, get_field_policy(fn))

    # ------------------------------------------------------------------
    # registry
    # ------------------------------------------------------------------

    def add_dissector(self, dissector: Optional[Dissector]) -> "Parser":
        if dissector is not None and dissector not in self.all_dissectors:
            self._assembled = False
            self.all_dissectors.append(dissector)
        return self

    def add_dissectors(self, dissectors: Sequence[Dissector]) -> "Parser":
        for d in dissectors:
            self.add_dissector(d)
        return self

    def drop_dissector(self, dissector_class: type) -> "Parser":
        self._assembled = False
        self.all_dissectors = [
            d for d in self.all_dissectors if type(d) is not dissector_class
        ]
        return self

    def set_root_type(self, new_root_type: str) -> "Parser":
        self._assembled = False
        self.root_type = new_root_type
        return self

    def ignore_missing_dissectors(self) -> "Parser":
        self._fail_on_missing_dissectors = False
        return self

    def fail_on_missing_dissectors(self) -> "Parser":
        self._fail_on_missing_dissectors = True
        return self

    # ------------------------------------------------------------------
    # parse targets
    # ------------------------------------------------------------------

    def add_parse_target(
        self,
        setter: Union[str, Callable],
        field_values: Union[str, Sequence[str]],
        setter_policy: SetterPolicy = SetterPolicy.ALWAYS,
    ) -> "Parser":
        self._assembled = False
        if isinstance(field_values, str):
            field_values = [field_values]

        if isinstance(setter, str):
            if self.record_class is None:
                raise InvalidFieldMethodSignature(
                    "Cannot resolve setter by name without a record class"
                )
            fn = getattr(self.record_class, setter, None)
            if fn is None:
                raise InvalidFieldMethodSignature(
                    f"No method {setter!r} on {self.record_class.__name__}"
                )
            method_name = setter
        else:
            fn = setter
            method_name = setter.__name__

        arg_count, value_type = _inspect_setter(self.record_class, fn)
        spec = _TargetSpec(method_name, arg_count, value_type, setter_policy)

        for fv in field_values:
            if fv is None:
                continue
            cleaned = cleanup_field_value(fv)
            if cleaned != fv:
                LOG.warning("The requested %r was converted into %r", fv, cleaned)
            self.targets.setdefault(cleaned, set()).add(spec)
        return self

    # ------------------------------------------------------------------
    # type remapping
    # ------------------------------------------------------------------

    def set_type_remappings(
        self, remappings: Optional[Dict[str, Set[str]]]
    ) -> "Parser":
        self.type_remappings = dict(remappings) if remappings else {}
        return self

    def add_type_remappings(self, additional: Dict[str, Set[str]]) -> "Parser":
        for inp, new_types in additional.items():
            for nt in new_types:
                self.add_type_remapping(inp, nt)
        return self

    def apply_config(
        self,
        type_remappings: Optional[Dict[str, Any]] = None,
        extra_dissectors: Optional[Sequence[Any]] = None,
    ) -> "Parser":
        """One-call string-config wiring shared by every adapter surface:
        remappings values may be a single type name or a collection."""
        for path, types in (type_remappings or {}).items():
            if isinstance(types, str):
                types = [types]
            for new_type in types:
                self.add_type_remapping(path, new_type)
        for dissector in extra_dissectors or ():
            self.add_dissector(dissector)
        return self

    def add_type_remapping(
        self,
        input_path: str,
        new_type: str,
        new_casts: FrozenSet[Cast] = STRING_ONLY,
    ) -> "Parser":
        self._assembled = False
        the_input = input_path.strip().lower()
        the_type = new_type.strip().upper()
        mappings = self.type_remappings.setdefault(the_input, set())
        if the_type not in mappings:
            mappings.add(the_type)
            self.casts_of_targets[the_type + ":" + the_input] = new_casts
        return self

    # ------------------------------------------------------------------
    # assembly
    # ------------------------------------------------------------------

    def get_needed(self) -> Set[str]:
        # Frozen after assembly so each per-line Parsable shares one set
        # instead of copying the target keys on the hot path.
        if self._assembled and self._needed_frozen is not None:
            return self._needed_frozen
        return set(self.targets.keys())

    def get_useful_intermediate_fields(self) -> Set[str]:
        return self._useful_intermediates

    def _assemble_dissector_phases(self) -> List[_DissectorPhase]:
        phases: List[_DissectorPhase] = []
        for dissector in self.all_dissectors:
            input_type = dissector.get_input_type()
            if input_type is None:
                raise InvalidDissectorException(
                    f"Dissector returns None on get_input_type(): [{type(dissector).__name__}]"
                )
            outputs = dissector.get_possible_output()
            if not outputs:
                raise InvalidDissectorException(
                    f"Dissector cannot create any outputs: [{type(dissector).__name__}]"
                )
            for output in outputs:
                out_type, _, name = output.partition(":")
                phases.append(_DissectorPhase(input_type, out_type, name, dissector))
        return phases

    def set_locale(self, locale) -> "Parser":
        """Timestamp locale for every locale-aware dissector (the rebuild's
        parser-level surface over TimeStampDissector.setLocale,
        TimeStampDissector.java:73-78).  Applies to dissectors already
        registered AND to ones added later during assembly (format tokens
        create their own strftime dissectors), so it may be called any
        time before parsing."""
        self._locale = locale
        for d in self.all_dissectors:
            if hasattr(d, "set_locale"):
                d.set_locale(locale)
        self._assembled = False  # re-prepare compiled instances
        return self

    def assemble_dissectors(self) -> None:
        if self._assembled:
            return
        if self.root_type is None:
            raise InvalidDissectorException("No root type was set")
        self.dissection_memo = {}  # targets may have changed since last run
        self._store_plans = {}
        self._fastline = _FASTLINE_UNSET  # recompiles after reassembly

        # Fixpoint: dissectors may register additional dissectors recursively.
        done: Set[int] = set()
        locale = getattr(self, "_locale", None)
        while True:
            pending = [d for d in self.all_dissectors if id(d) not in done]
            if not pending:
                break
            for d in pending:
                done.add(id(d))
                if locale is not None and hasattr(d, "set_locale"):
                    d.set_locale(locale)
                d.create_additional_dissectors(self)

        available = self._assemble_dissector_phases()

        needed = self.get_needed()
        needed.add(self.root_type + ":")  # the root name is an empty string

        all_possible_subtargets: Set[str] = set()
        for need in needed:
            needed_name = need.split(":", 1)[1]
            acc = ""
            for part in needed_name.split("."):
                acc = part if (acc == "" or part == "") else acc + "." + part
                all_possible_subtargets.add(acc)

        self._compiled = {}
        self._useful_intermediates = set()
        self._located_targets = set()
        self._find_useful_dissectors(
            available, all_possible_subtargets, self.root_type, "", True
        )

        for phase_list in self._compiled.values():
            for phase in phase_list:
                phase.instance.prepare_for_run()

        if not self._compiled:
            raise MissingDissectorsException(
                "There are no dissectors at all which makes this a completely useless parser."
            )

        if self._fail_on_missing_dissectors:
            missing = self._get_missing_fields()
            if missing:
                raise MissingDissectorsException("\n".join(sorted(missing)))
        self._needed_frozen = frozenset(self.targets.keys())
        self._prepare_last_chance_converters(available)
        self._assembled = True

    def _prepare_last_chance_converters(
        self, available: List[_DissectorPhase]
    ) -> None:
        """Precompute the per-needed-id converter candidates for the
        last-chance pass (see _last_chance_converters): one prepared,
        stateless instance per (needed id), casts registered HERE so parse()
        never mutates shared parser state."""
        self._last_chance: Dict[str, List[Tuple[str, Any]]] = {}
        for nid in self._needed_frozen:
            if nid.endswith("*"):
                continue
            ftype, _, path = nid.partition(":")
            for phase in available:
                if phase.output_type != ftype or phase.name != "":
                    continue
                # Keep EVERY candidate (not just the first): two converters
                # with different input types can produce the same needed
                # type, and which input is cached depends on the line.
                instance = phase.instance.get_new_instance()
                self.casts_of_targets.setdefault(
                    nid, instance.prepare_for_dissect(path, path)
                )
                instance.prepare_for_run()  # full SPI lifecycle, like any phase
                self._last_chance.setdefault(nid, []).append(
                    (phase.input_type, instance)
                )

    def _find_useful_dissectors(
        self,
        available: List[_DissectorPhase],
        possible_targets: Set[str],
        sub_root_type: str,
        sub_root_name: str,
        this_is_the_root: bool,
    ) -> None:
        sub_root_id = sub_root_type + ":" + sub_root_name
        if sub_root_id in self._located_targets:
            return  # avoid infinite recursion
        self._located_targets.add(sub_root_id)

        for phase in available:
            if phase.input_type != sub_root_type:
                continue

            check_fields: Set[str] = set()
            if phase.name == "*":
                # Wildcard output: match requested paths under this prefix.
                prefix = sub_root_name + "."
                for target in possible_targets:
                    if target.startswith(prefix):
                        check_fields.add(target)
            elif this_is_the_root:
                check_fields.add(phase.name)
            elif phase.name == "":
                check_fields.add(sub_root_name)
            else:
                check_fields.add(sub_root_name + "." + phase.name)

            for check_field in check_fields:
                out_id = phase.output_type + ":" + check_field
                if check_field in possible_targets and out_id not in self._compiled:
                    node_phases = self._compiled.get(sub_root_id)
                    if node_phases is None:
                        node_phases = []
                        self._compiled[sub_root_id] = node_phases
                        self._useful_intermediates.add(sub_root_name)

                    instance_phase = None
                    for p in node_phases:
                        if type(p.instance) is type(phase.instance):
                            instance_phase = p
                            break
                    if instance_phase is None:
                        instance_phase = _DissectorPhase(
                            phase.input_type,
                            phase.output_type,
                            check_field,
                            phase.instance.get_new_instance(),
                        )
                        node_phases.append(instance_phase)

                    self.casts_of_targets[out_id] = instance_phase.instance.prepare_for_dissect(
                        sub_root_name, check_field
                    )
                    self._find_useful_dissectors(
                        available, possible_targets, phase.output_type, check_field, False
                    )

        mappings = self.type_remappings.get(sub_root_name)
        if mappings:
            for mapped_type in mappings:
                if (mapped_type + ":" + sub_root_name) not in self._compiled:
                    # Retyped targets are ALWAYS string-only.
                    self.casts_of_targets[mapped_type + ":" + sub_root_name] = STRING_ONLY
                    self._find_useful_dissectors(
                        available, possible_targets, mapped_type, sub_root_name, False
                    )

    def _get_missing_fields(self) -> Set[str]:
        missing: Set[str] = set()
        for target in self.get_needed():
            if target in self._located_targets:
                continue
            if target.endswith("*"):
                if target.endswith(".*"):
                    if target[:-2] not in self._located_targets:
                        missing.add(target)
                # else: ends with ":*" — always "present"
            else:
                missing.add(target)
        return missing

    # ------------------------------------------------------------------
    # parse
    # ------------------------------------------------------------------

    def create_parsable(self, record: Optional[Any] = None) -> Parsable:
        if record is None:
            if self.record_class is None:
                raise InvalidDissectorException("No record class and no record instance")
            record = self.record_class()
        return Parsable(self, record, self.type_remappings)

    def parse(self, value: str, record: Optional[Any] = None) -> Any:
        """Parse one line; returns the (new or given) record."""
        self.assemble_dissectors()
        if self.use_fastline:
            engine = self._fastline
            if engine is _FASTLINE_UNSET:
                from .fastline import compile_fastline

                engine = self._fastline = compile_fastline(self)
            if engine is not None:
                if record is None:
                    if self.record_class is None:
                        raise InvalidDissectorException(
                            "No record class and no record instance"
                        )
                    record = self.record_class()
                return engine.parse(value, record)
        parsable = self.create_parsable(record)
        parsable.set_root_dissection(self.root_type, value)
        self._run(parsable)
        return parsable.get_record()

    def parse_many(self, lines, record_factory) -> List[Optional[Any]]:
        """Batched parse with amortized setup: one engine fetch for the
        whole batch (the per-call dispatch in :meth:`parse` was a
        measurable share of small-rescue cost), one fresh record per
        line.  Returns the parsed record per line, None where the line
        raised DissectionFailure, and an
        :class:`~logparser_tpu.core.exceptions.OracleEngineError` marker
        where the ENGINE itself raised — the shape the batch runtime's
        rescue path consumes.  One broken line must cost itself a
        reasoned reject, never abort the other N-1 lines of the rescue
        batch (the per-line :meth:`parse` keeps raising for its own
        callers)."""
        self.assemble_dissectors()
        if self.use_fastline:
            engine = self._fastline
            if engine is _FASTLINE_UNSET:
                from .fastline import compile_fastline

                engine = self._fastline = compile_fastline(self)
            if engine is not None:
                return engine.parse_many(lines, record_factory)
        from .exceptions import OracleEngineError

        out: List[Optional[Any]] = []
        for line in lines:
            record = record_factory()
            try:
                parsable = self.create_parsable(record)
                parsable.set_root_dissection(self.root_type, line)
                self._run(parsable)
                out.append(parsable.get_record())
            except DissectionFailure:
                out.append(None)
            except Exception as e:  # noqa: BLE001 — engine fault, per line
                out.append(OracleEngineError(f"{type(e).__name__}: {e}"))
        return out

    def _run(self, parsable: Parsable) -> Parsable:
        to_be_parsed = set(parsable.to_be_parsed)
        while to_be_parsed:
            for pf in to_be_parsed:
                parsable.set_as_parsed(pf)
                for phase in self._compiled.get(pf.id, ()):
                    phase.instance.dissect(parsable, pf.name)
            to_be_parsed = set(parsable.to_be_parsed)
        self._last_chance_converters(parsable)
        return parsable

    def _last_chance_converters(self, parsable: Parsable) -> None:
        """Deliver needed ids the compiled tree missed but a pure type
        converter can still produce from the cache.

        The compile guard (`out_id not in _compiled`) wires only ONE
        direction of a converter cycle — necessary for parse termination —
        so with two producers of the same path under different types (e.g.
        `%B ... %b` across two LogFormats plus the CLF<->number
        translators), the direction a given line needs may be the one that
        lost the compile race.  This one-shot, non-recursive pass applies a
        whole-path converter phase (name == "") to a cached field of the
        same path; it cannot loop and is a no-op when everything was
        delivered."""
        candidates = self._last_chance
        if not candidates:
            return
        for nid, options in candidates.items():
            if nid in parsable.delivered:
                continue
            _, _, path = nid.partition(":")
            for input_type, instance in options:
                if parsable.get_parsable_field(input_type, path) is not None:
                    instance.dissect(parsable, path)
                    break

    # ------------------------------------------------------------------
    # store (setter dispatch)
    # ------------------------------------------------------------------

    def _build_store_plan(self, key: str, name: str):
        """Resolve the per-delivery dispatch for one target key ONCE:
        AUTO value types and cast-membership checks are line-invariant, so
        the hot `store` loop reduces to value conversion + policy check +
        the setter call.  Returns (resolved_specs, casts_to) or None after
        logging (unknown key / no casts — matching the uncached errors)."""
        specs = self.targets.get(key)
        if specs is None:
            LOG.error("NO methods for key=%s name=%s", key, name)
            return None
        casts_to = self.casts_of_targets.get(key)
        if casts_to is None:
            casts_to = self.casts_of_targets.get(name)
            if casts_to is None:
                LOG.error('NO casts for "%s"', name)
                return None
        resolved = []
        for spec in specs:
            vtype = spec.value_type
            if vtype == "AUTO":
                if Cast.STRING in casts_to:
                    vtype = "STRING"
                elif Cast.LONG in casts_to:
                    vtype = "LONG"
                elif Cast.DOUBLE in casts_to:
                    vtype = "DOUBLE"
                else:
                    continue
            if vtype == "STRING" and Cast.STRING not in casts_to:
                continue
            if vtype == "LONG" and Cast.LONG not in casts_to:
                continue
            if vtype == "DOUBLE" and Cast.DOUBLE not in casts_to:
                continue
            resolved.append((
                spec.method_name,
                spec.arg_count,
                vtype,
                spec.policy is not SetterPolicy.ALWAYS,     # skip None
                spec.policy is SetterPolicy.NOT_EMPTY,
            ))
        return tuple(resolved), casts_to

    def store(self, record: Any, key: str, name: str, value: Value) -> None:
        # The dispatch plan is line-invariant per key; wildcard keys fall
        # back to per-name casts, so those cache under (key, name).
        plans = self._store_plans
        plan = plans.get(key)
        if plan is None:
            cache_key: Any = key
            if key not in self.casts_of_targets:
                cache_key = (key, name)
                plan = plans.get(cache_key)
            if plan is None:
                plan = self._build_store_plan(key, name)
                if plan is None:
                    return
                plans[cache_key] = plan
        resolved, casts_to = plan

        called_a_setter = False
        for method_name, arg_count, vtype, skip_null, not_empty in resolved:
            if vtype == "STRING":
                out: Any = value.get_string()
            elif vtype == "LONG":
                out = value.get_long()
            else:
                out = value.get_double()

            if out is None and skip_null:
                called_a_setter = True
                continue
            if not_empty and vtype == "STRING" and out == "":
                called_a_setter = True
                continue

            method = getattr(record, method_name, None)
            if method is None:
                raise FatalErrorDuringCallOfSetterMethod(
                    f"Record {type(record).__name__} has no method {method_name!r}"
                )
            try:
                if arg_count == 2:
                    method(name, out)
                else:
                    method(out)
            except Exception as e:  # noqa: BLE001 — mirror FatalError wrapping
                raise FatalErrorDuringCallOfSetterMethod(
                    f'{e} when calling "{method_name}" for key="{key}" '
                    f'name="{name}" value="{value}" casts_to="{casts_to}"'
                ) from e
            called_a_setter = True

        if not called_a_setter:
            raise FatalErrorDuringCallOfSetterMethod(
                f'No setter called for key="{key}" name="{name}" value="{value}"'
            )

    # ------------------------------------------------------------------
    # discovery
    # ------------------------------------------------------------------

    def get_possible_paths(self, max_depth: int = 15) -> List[str]:
        if not self.all_dissectors:
            return []
        try:
            self.assemble_dissectors()
        except (MissingDissectorsException, InvalidDissectorException):
            pass

        paths: List[str] = []
        path_nodes: Dict[str, List[str]] = {}
        for dissector in self.all_dissectors:
            input_type = dissector.get_input_type()
            if input_type is None:
                LOG.error(
                    "Dissector returns None on get_input_type(): [%s]",
                    type(dissector).__name__,
                )
                return []
            outputs = list(dissector.get_possible_output())
            existing = path_nodes.get(input_type)
            if existing:
                outputs.extend(existing)
            path_nodes[input_type] = outputs

        self._find_additional_possible_paths(path_nodes, paths, "", self.root_type, max_depth)

        for input_path, new_types in self.type_remappings.items():
            for new_type in new_types:
                paths.append(new_type + ":" + input_path)
                self._find_additional_possible_paths(
                    path_nodes, paths, input_path, new_type, max_depth - 1
                )
        return paths

    def _find_additional_possible_paths(
        self,
        path_nodes: Dict[str, List[str]],
        paths: List[str],
        base: str,
        base_type: str,
        max_depth: int,
    ) -> None:
        if max_depth == 0:
            return
        for child_path in path_nodes.get(base_type, ()):
            child_type, _, child_name = child_path.partition(":")
            if base == "":
                child_base = child_name
            elif child_name == "":
                child_base = base
            else:
                child_base = base + "." + child_name
            new_path = child_type + ":" + child_base
            if new_path not in paths:
                paths.append(new_path)
                self._find_additional_possible_paths(
                    path_nodes, paths, child_base, child_type, max_depth - 1
                )

    def get_casts(self, path: str) -> Optional[FrozenSet[Cast]]:
        """Casts available for a path (requires the path to be a parse target)."""
        try:
            self.assemble_dissectors()
        except (MissingDissectorsException, InvalidDissectorException):
            pass
        return self.casts_of_targets.get(cleanup_field_value(path))

    # ------------------------------------------------------------------
    # pickling — drop compiled per-node state; reassemble lazily on load
    # (the Java parser re-resolves reflection Methods the same way,
    # Parser.java:91-97, 242-277)
    # ------------------------------------------------------------------

    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        state["_assembled"] = False
        state["_compiled"] = {}
        state["_useful_intermediates"] = set()
        state["_located_targets"] = set()
        state["_needed_frozen"] = None
        state["_last_chance"] = {}
        # Drop the compiled engine AND the sentinel: the sentinel is
        # identity-compared, so it must be restored from this module on
        # load, never round-tripped through the pickle.
        state.pop("_fastline", None)
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self.__dict__["_fastline"] = _FASTLINE_UNSET
        self.__dict__.setdefault("use_fastline", True)
