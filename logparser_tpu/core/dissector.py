"""Dissector SPI — the unit of dissection.

Reference behavior: parser-core/.../core/Dissector.java:29-186.  Three-phase
lifecycle documented at Dissector.java:29-61:

1. setup — construct + configure (e.g. set_log_format), or string-config via
   ``initialize_from_settings_parameter`` (Dissector.java:75) for dynamic loading.
2. per-graph-node instancing — the parser clones a dissector per tree node via
   ``get_new_instance``/``initialize_new_instance`` (Dissector.java:135-165), then
   calls ``prepare_for_dissect(input_name, output_name)`` once per demanded output
   (returns the casts for that output) and finally ``prepare_for_run`` once.
3. run — many ``dissect(parsable, input_name)`` calls, one per input field value.

``create_additional_dissectors`` (Dissector.java:173) lets a dissector register
helper dissectors on the parser (run to fixpoint during assembly).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, FrozenSet, List, Optional

from .casts import Cast, STRING_ONLY
from .fields import ParsedField

if TYPE_CHECKING:  # pragma: no cover
    from .parsable import Parsable
    from .parser import Parser


def extract_field_name(input_name: str, output_name: str) -> str:
    """The relative output name below the input name
    (Dissector.extractFieldName, Dissector.java:147-157): equal names yield
    the empty relative name (used by empty-named outputs)."""
    if input_name == output_name:
        return ""
    if input_name and output_name.startswith(input_name + "."):
        return output_name[len(input_name) + 1 :]
    return output_name


class Dissector:
    """Abstract dissector. Subclasses declare input type + possible outputs and
    implement ``dissect``."""

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        """String-config entry point used by engine adapters that load dissectors
        dynamically from a single string parameter. True = success."""
        return False

    def dissect(self, parsable: "Parsable", input_name: str) -> None:
        raise NotImplementedError

    def get_input_type(self) -> str:
        raise NotImplementedError

    def set_input_type(self, new_input_type: str) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} does not support set_input_type"
        )

    def get_possible_output(self) -> List[str]:
        """List of ``TYPE:name`` outputs this dissector can produce."""
        raise NotImplementedError

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        """Called during assembly for every demanded output; returns its casts.
        Dissectors use this to learn which outputs to actually compute."""
        return STRING_ONLY

    def prepare_for_run(self) -> None:
        """Called once after all prepare_for_dissect calls; compile here."""

    def get_new_instance(self) -> "Dissector":
        new = type(self)()
        self.initialize_new_instance(new)
        return new

    def initialize_new_instance(self, new_instance: "Dissector") -> None:
        """Copy configuration onto a freshly constructed clone."""

    def create_additional_dissectors(self, parser: "Parser") -> None:
        """Register helper dissectors on the parser (may recurse via fixpoint)."""


class SimpleDissector(Dissector):
    """Convenience base with a declarative ``{output path -> casts}`` map.

    Reference behavior: parser-core/.../core/SimpleDissector.java:38-89 — the
    constructor records input type and output map; ``dissect`` fetches the input
    field and delegates to ``dissect_value``.
    """

    def __init__(self, input_type: str, outputs: Dict[str, FrozenSet[Cast]]):
        self._input_type = input_type
        # output config: "TYPE:name" -> (type, name, casts)
        self._output_casts: Dict[str, FrozenSet[Cast]] = {}
        self._outputs: List[str] = []
        for path, casts in outputs.items():
            self._outputs.append(path)
            self._output_casts[path] = casts

    def get_input_type(self) -> str:
        return self._input_type

    def set_input_type(self, new_input_type: str) -> None:
        self._input_type = new_input_type

    def get_possible_output(self) -> List[str]:
        return list(self._outputs)

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        for path, casts in self._output_casts.items():
            name = path.split(":", 1)[1]
            # An empty output name is a 1:1 type edge (the translate/
            # dissectors): the output IS the input path, any name matches
            # (TypeConvertBaseDissector semantics).
            if name == "" or output_name == name or output_name.endswith("." + name):
                return casts
        return STRING_ONLY

    def get_new_instance(self) -> "Dissector":
        import copy

        new = copy.copy(self)
        self.initialize_new_instance(new)
        return new

    def dissect(self, parsable: "Parsable", input_name: str) -> None:
        parsed_field: Optional[ParsedField] = parsable.get_parsable_field(
            self._input_type, input_name
        )
        if parsed_field is not None:
            self.dissect_field(parsable, input_name, parsed_field)

    def dissect_field(
        self, parsable: "Parsable", input_name: str, parsed_field: ParsedField
    ) -> None:
        raise NotImplementedError
