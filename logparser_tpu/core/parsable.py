"""Per-line mutable parse state for the host (oracle) execution path.

Reference behavior: parser-core/.../core/Parsable.java:40-219 — keeps a cache of
intermediate ParsedFields, a worklist of fields still to be dissected, and routes
finished values to the parser's store().  addDissection computes the complete
dotted name, applies type remappings (recursively, once), caches useful
intermediates, and stores values that are needed directly or via a wildcard
(``TYPE:base.*``) target.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Any, Dict, Optional, Set, Union

from .exceptions import DissectionFailure
from .fields import ParsedField, make_field_id
from .value import Value

if TYPE_CHECKING:  # pragma: no cover
    from .parser import Parser


class Parsable:
    def __init__(
        self,
        parser: "Parser",
        record: Any,
        type_remappings: Dict[str, Set[str]],
    ):
        self.parser = parser
        self.record = record
        self.type_remappings = type_remappings
        self.needed: Set[str] = parser.get_needed()
        self.useful_intermediates: Set[str] = parser.get_useful_intermediate_fields()
        self._cache: Dict[str, ParsedField] = {}
        self.to_be_parsed: Set[ParsedField] = set()
        # Exact needed ids actually delivered to the record (drives the
        # last-chance converter pass in Parser._run).
        self.delivered: Set[str] = set()

    def set_root_dissection(self, root_type: str, value: Union[str, Value]) -> None:
        pf = ParsedField(root_type, "", value)  # the root name is an empty string
        self._cache[pf.id] = pf
        self.to_be_parsed.add(pf)

    def add_dissection(
        self,
        base: str,
        ftype: str,
        name: str,
        value: Union[Value, str, int, float, None],
        _recursion: bool = False,
    ) -> "Parsable":
        # Dissectors add every output they produce; most are unwanted, and
        # the routing decision for a given (base, type, name) triple is
        # LINE-INVARIANT — memoize it on the parser so the common unwanted
        # case costs one dict probe and no object construction.
        memo = self.parser.dissection_memo
        entry = memo.get((base, ftype, name))
        if entry is None:
            if base == "":  # the root name is an empty string
                complete_name = name
                needed_wildcard = ftype + ":*"
            else:
                complete_name = base if name == "" else base + "." + name
                needed_wildcard = ftype + ":" + base + ".*"
            needed_name = ftype + ":" + complete_name
            remapped = self.type_remappings.get(complete_name)
            entry = (
                tuple(remapped) if remapped else (),
                complete_name in self.useful_intermediates,
                needed_name in self.needed,
                needed_wildcard in self.needed,
                complete_name,
                needed_name,
                needed_wildcard,
            )
            memo[(base, ftype, name)] = entry
        (remapped_types, is_intermediate, is_needed, is_wild,
         complete_name, needed_name, needed_wildcard) = entry

        if not _recursion:
            for new_type in remapped_types:
                if new_type == ftype:
                    raise DissectionFailure(
                        "[Type Remapping] Trying to map to the same type "
                        f"(mapping definition bug!): base={base} type={ftype} name={name}"
                    )
                self.add_dissection(base, new_type, name, value, _recursion=True)

        if not (is_intermediate or is_needed or is_wild):
            return self

        if not isinstance(value, Value):
            value = Value(value)

        if is_intermediate:
            pf = ParsedField(ftype, complete_name, value)
            self._cache[pf.id] = pf
            self.to_be_parsed.add(pf)

        if is_needed:
            self.delivered.add(needed_name)
            self.parser.store(self.record, needed_name, needed_name, value)

        if is_wild:
            self.parser.store(self.record, needed_wildcard, needed_name, value)
        return self

    def get_parsable_field(self, ftype: str, name: str) -> Optional[ParsedField]:
        return self._cache.get(make_field_id(ftype, name))

    def get_record(self) -> Any:
        return self.record

    def set_as_parsed(self, parsed_field: ParsedField) -> None:
        self.to_be_parsed.discard(parsed_field)
