"""Field identity, parsed-field triple, setter policies, and the @field decorator.

Reference behavior:
- Field ids are ``TYPE:dotted.path`` strings; TYPE uppercased, path lowercased
  (parser-core/.../core/Parser.java:681-691 cleanupFieldValue).
- ParsedField = (type, name, Value); id via makeId (ParsedField.java:53).
- @Field annotation marks record setters with wanted paths + SetterPolicy
  (Field.java:31-35, Parser.java:51-60).  Here: a decorator that tags methods.
"""
from __future__ import annotations

import enum
from typing import Callable, Iterable, List, Optional, Sequence, Union

from .value import Value


class SetterPolicy(enum.Enum):
    """When a setter is invoked relative to null/empty values.

    Reference: Parser.java:51-60 — ALWAYS calls with whatever value (possibly
    None); NOT_NULL skips None; NOT_EMPTY skips None and empty strings.
    """

    ALWAYS = "ALWAYS"
    NOT_NULL = "NOT_NULL"
    NOT_EMPTY = "NOT_EMPTY"


def cleanup_field_value(field_value: str) -> str:
    """Normalize ``TYPE:path`` — TYPE upper, path lower (Parser.java:681-691)."""
    colon = field_value.find(":")
    if colon == -1:
        return field_value.lower()
    return field_value[:colon].upper() + ":" + field_value[colon + 1 :].lower()


def make_field_id(ftype: str, name: str) -> str:
    return f"{ftype}:{name}"


class ParsedField:
    """(type, name, value) triple; identity is the ``TYPE:name`` id string."""

    __slots__ = ("type", "name", "value", "id")

    def __init__(self, ftype: str, name: str, value: Union[Value, str, int, float, None]):
        if not isinstance(value, Value):
            value = Value(value)
        self.type = ftype
        self.name = name
        self.value = value
        self.id = make_field_id(ftype, name)

    def __repr__(self) -> str:
        return f"ParsedField({self.id}={self.value!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ParsedField) and other.id == self.id

    def __hash__(self) -> int:
        return hash(self.id)


_FIELD_ATTR = "__logparser_fields__"
_POLICY_ATTR = "__logparser_setter_policy__"


def field(
    *paths: Union[str, Sequence[str]],
    setter_policy: SetterPolicy = SetterPolicy.ALWAYS,
) -> Callable:
    """Decorator marking a record method as a parse target for the given paths.

    Python analogue of the reference's ``@Field`` annotation (Field.java:31-35)::

        class MyRecord:
            @field("IP:connection.client.host")
            def set_ip(self, value: str): ...

            @field("STRING:request.firstline.uri.query.*")
            def set_query_param(self, name: str, value: str): ...

    The value-parameter's type annotation (str/int/float) selects which cast is
    delivered, mirroring the Java setter-signature dispatch (Parser.java:590-603).
    """
    flat: List[str] = []
    for p in paths:
        if isinstance(p, str):
            flat.append(p)
        else:
            flat.extend(p)

    def mark(fn: Callable) -> Callable:
        setattr(fn, _FIELD_ATTR, flat)
        setattr(fn, _POLICY_ATTR, setter_policy)
        return fn

    return mark


def get_field_paths(fn: Callable) -> Optional[List[str]]:
    return getattr(fn, _FIELD_ATTR, None)


def get_field_policy(fn: Callable) -> SetterPolicy:
    return getattr(fn, _POLICY_ATTR, SetterPolicy.ALWAYS)
