"""Cast sets: which typed representations a produced field value supports.

Reference behavior: parser-core/src/main/java/nl/basjes/parse/core/Casts.java:22-31
(enum STRING/LONG/DOUBLE plus canned EnumSets). We use frozensets of a small enum.
"""
from __future__ import annotations

import enum


class Cast(enum.Enum):
    STRING = "STRING"
    LONG = "LONG"
    DOUBLE = "DOUBLE"

    def __repr__(self) -> str:  # terse in test failure tables
        return self.value


NO_CASTS: frozenset[Cast] = frozenset()
STRING_ONLY: frozenset[Cast] = frozenset({Cast.STRING})
LONG_ONLY: frozenset[Cast] = frozenset({Cast.LONG})
DOUBLE_ONLY: frozenset[Cast] = frozenset({Cast.DOUBLE})
STRING_OR_LONG: frozenset[Cast] = frozenset({Cast.STRING, Cast.LONG})
STRING_OR_DOUBLE: frozenset[Cast] = frozenset({Cast.STRING, Cast.DOUBLE})
LONG_OR_DOUBLE: frozenset[Cast] = frozenset({Cast.LONG, Cast.DOUBLE})
STRING_OR_LONG_OR_DOUBLE: frozenset[Cast] = frozenset(
    {Cast.STRING, Cast.LONG, Cast.DOUBLE}
)
