"""Engine-agnostic core runtime: Parser, Parsable, Dissector SPI, field identity.

TPU-native rebuild of the reference's parser-core layer
(/root/reference/parser-core/src/main/java/nl/basjes/parse/core/).
"""
from .casts import (
    Cast,
    DOUBLE_ONLY,
    LONG_ONLY,
    LONG_OR_DOUBLE,
    NO_CASTS,
    STRING_ONLY,
    STRING_OR_DOUBLE,
    STRING_OR_LONG,
    STRING_OR_LONG_OR_DOUBLE,
)
from .dissector import Dissector, SimpleDissector
from .exceptions import (
    DissectionFailure,
    FatalErrorDuringCallOfSetterMethod,
    InvalidDissectorException,
    InvalidFieldMethodSignature,
    MissingDissectorsException,
)
from .fields import ParsedField, SetterPolicy, cleanup_field_value, field, make_field_id
from .parsable import Parsable
from .parser import Parser
from .value import Value

__all__ = [
    "Cast",
    "NO_CASTS",
    "STRING_ONLY",
    "LONG_ONLY",
    "DOUBLE_ONLY",
    "STRING_OR_LONG",
    "STRING_OR_DOUBLE",
    "LONG_OR_DOUBLE",
    "STRING_OR_LONG_OR_DOUBLE",
    "Dissector",
    "SimpleDissector",
    "DissectionFailure",
    "MissingDissectorsException",
    "InvalidDissectorException",
    "InvalidFieldMethodSignature",
    "FatalErrorDuringCallOfSetterMethod",
    "ParsedField",
    "SetterPolicy",
    "field",
    "cleanup_field_value",
    "make_field_id",
    "Parsable",
    "Parser",
    "Value",
]
