"""Tri-state value union (string / int / float) with lazy cross-casts.

Reference behavior: parser-core/.../core/Value.java:48-87 — string->long via integer
parse (None on failure), string->double via float parse (None on failure),
double->long with round-half-up (floor(d + 0.5)), long->string/double trivially.
"""
from __future__ import annotations

import math
from typing import Optional, Union

RawValue = Union[str, int, float, None]


def _java_double_to_string(d: float) -> str:
    """Match Java's Double.toString: shortest decimal that round-trips, plain
    decimal form for 1e-3 <= |d| < 1e7, otherwise ``d.dddEn`` scientific form,
    always with at least one digit after the point."""
    if math.isnan(d):
        return "NaN"
    if math.isinf(d):
        return "Infinity" if d > 0 else "-Infinity"
    if d == 0.0:
        return "-0.0" if math.copysign(1.0, d) < 0 else "0.0"
    a = abs(d)
    if 1e-3 <= a < 1e7:
        # Python repr is also shortest-round-trip and stays in decimal form
        # (no exponent) throughout this magnitude range.
        return repr(d)
    from decimal import Decimal

    sign, digits, exp = Decimal(repr(a)).as_tuple()
    e = exp + len(digits) - 1
    mant_digits = "".join(map(str, digits)).rstrip("0") or "0"
    mant = (
        mant_digits + ".0"
        if len(mant_digits) == 1
        else mant_digits[0] + "." + mant_digits[1:]
    )
    return ("-" if d < 0 else "") + mant + "E" + str(e)


_LONG_MIN = -(2**63)
_LONG_MAX = 2**63 - 1


def _parse_java_long(s: str) -> Optional[int]:
    """Long.parseLong semantics: optional sign, decimal digits only, 64-bit range."""
    if not s:
        return None
    body = s[1:] if s[0] in "+-" else s
    if not body or not body.isascii() or not body.isdigit():
        return None
    try:
        v = int(s)
    except ValueError:
        return None
    if v < _LONG_MIN or v > _LONG_MAX:
        return None
    return v


def _parse_java_double(s: str) -> Optional[float]:
    """Double.parseDouble semantics (no underscores, no 'inf'/'nan' spellings
    beyond Java's, which log data never contains)."""
    if not s:
        return None
    t = s.strip()
    if not t or "_" in t:
        return None
    # Python accepts 'inf'/'nan' like Java accepts 'Infinity'/'NaN'; log fields
    # never legitimately carry either, so reject the textual forms Java rejects.
    low = t.lower().lstrip("+-")
    if low in ("inf", "infinity", "nan"):
        return None
    try:
        return float(t)
    except ValueError:
        return None


class Value:
    """One parsed field value; remembers which representation filled it."""

    __slots__ = ("_kind", "_v")

    def __init__(self, v: RawValue, kind: Optional[str] = None):
        if kind is None:
            if v is None or isinstance(v, str):
                kind = "STRING"
            elif isinstance(v, bool):
                raise TypeError("bool is not a valid Value payload")
            elif isinstance(v, int):
                kind = "LONG"
            elif isinstance(v, float):
                kind = "DOUBLE"
            else:
                raise TypeError(f"unsupported value type: {type(v)!r}")
        self._kind = kind
        self._v = v

    @property
    def kind(self) -> str:
        return self._kind

    def get_string(self) -> Optional[str]:
        if self._v is None:
            return None
        if self._kind == "LONG":
            return str(self._v)
        if self._kind == "DOUBLE":
            return _java_double_to_string(float(self._v))
        return self._v  # type: ignore[return-value]

    def get_long(self) -> Optional[int]:
        if self._v is None:
            return None
        if self._kind == "STRING":
            return _parse_java_long(self._v)  # type: ignore[arg-type]
        if self._kind == "DOUBLE":
            d = float(self._v)
            # Java: (long) Math.floor(d + 0.5) — NaN -> 0, +/-inf and overflow
            # clamp to Long.MAX/MIN.
            if math.isnan(d):
                return 0
            if d >= _LONG_MAX:
                return _LONG_MAX
            if d <= _LONG_MIN:
                return _LONG_MIN
            return int(math.floor(d + 0.5))
        return int(self._v)  # type: ignore[arg-type]

    def get_double(self) -> Optional[float]:
        if self._v is None:
            return None
        if self._kind == "STRING":
            return _parse_java_double(self._v)  # type: ignore[arg-type]
        return float(self._v)  # type: ignore[arg-type]

    def __repr__(self) -> str:
        return f"Value({self._kind}:{self._v!r})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Value)
            and other._kind == self._kind
            and other._v == self._v
        )

    def __hash__(self) -> int:
        return hash((self._kind, self._v))
