"""Engine exceptions.

Reference behavior: parser-core/.../core/exceptions/*.java — DissectionFailure is
the recoverable per-line failure; the others are configuration/API errors raised
during parser assembly.
"""
from __future__ import annotations


class DissectionFailure(Exception):
    """A single line could not be dissected (recoverable; callers skip/count)."""


class OracleEngineError:
    """Per-line MARKER (not an exception): the host oracle itself failed
    on this line — an engine bug or a pathological input tripping a code
    path no DissectionFailure covers.  Batched rescue (``parse_many``)
    returns it in place of the record so ONE such line costs itself, not
    the whole rescue batch, and downstream consumers surface it as a
    counted, reasoned reject (``reason="oracle_error"``) instead of a
    silent ``None`` or a batch-aborting raise.  Picklable — it rides the
    spawn-pool result path."""

    __slots__ = ("error",)

    def __init__(self, error: str):
        self.error = error

    def __repr__(self) -> str:  # pragma: no cover - debugging surface
        return f"OracleEngineError({self.error!r})"


class MissingDissectorsException(Exception):
    """Requested fields cannot be produced by any dissector chain."""


class InvalidDissectorException(Exception):
    """A dissector is malformed (no input type, no outputs, ...)."""


class InvalidFieldMethodSignature(Exception):
    """A parse-target callable has an unsupported signature."""


class FatalErrorDuringCallOfSetterMethod(Exception):
    """A record setter raised, or no setter accepted a stored value."""
