"""Engine exceptions.

Reference behavior: parser-core/.../core/exceptions/*.java — DissectionFailure is
the recoverable per-line failure; the others are configuration/API errors raised
during parser assembly.
"""
from __future__ import annotations


class DissectionFailure(Exception):
    """A single line could not be dissected (recoverable; callers skip/count)."""


class MissingDissectorsException(Exception):
    """Requested fields cannot be produced by any dissector chain."""


class InvalidDissectorException(Exception):
    """A dissector is malformed (no input type, no outputs, ...)."""


class InvalidFieldMethodSignature(Exception):
    """A parse-target callable has an unsupported signature."""


class FatalErrorDuringCallOfSetterMethod(Exception):
    """A record setter raised, or no setter accepted a stored value."""
