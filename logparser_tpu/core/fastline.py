"""Compiled per-format store programs for the per-line host engine.

The generic engine (Parser._run) routes every dissector output through
``Parsable.add_dissection`` — a memoized but still per-output dict-probe +
object-construction path — and re-discovers the same line-invariant routing
decisions on every line (reference hot loop: Parser.java:726-756 +
Parsable.java:142-193).  This module compiles that routing ONCE per
assembled parser into flat per-format programs:

- the LogFormat regex match feeds token captures straight into precompiled
  *routes* (direct setter dispatch with resolved store plans — the
  Parser.store inner loop of Parser.java:760-876 with every line-invariant
  decision hoisted),
- the hot sub-dissectors (timestamp, first line, protocol split, the
  translate converters) compile to *value-level emitters* whose outputs
  feed further precompiled routes,
- anything else (URI repair, wildcards, GeoIP, ...) falls back to the
  UNMODIFIED generic dissector running against a real Parsable, so the
  messy byte-level semantics stay single-sourced.

Semantics contract: identical delivered records and identical
DissectionFailure behavior vs the generic engine — locked by
tests/test_fastline.py differential sweeps.  compile_fastline returns None
whenever a construct it cannot faithfully replay is present (stateful
multi-format switching, a non-HttpdLogFormat root, ...); the caller then
keeps the generic path.
"""
from __future__ import annotations

import os

from typing import Any, Callable, Dict, List, Optional, Tuple

from .exceptions import DissectionFailure, FatalErrorDuringCallOfSetterMethod
from .fields import ParsedField, make_field_id
from .value import Value, _java_double_to_string, _parse_java_double, _parse_java_long

_IN_PROGRESS = object()

Route = Callable[["_Ctx", Any], None]

# Escape hatch: LOGPARSER_TPU_FASTLINE_INTERP=1 keeps the interpreted
# route closures (no source generation) — the bit-exactness referee the
# codegen differential suite compares against, and the fallback if a
# construct ever trips the generator in production.
_INTERP_ENV = "LOGPARSER_TPU_FASTLINE_INTERP"


class _Ctx:
    """Per-line mutable state for the compiled path."""

    __slots__ = ("record", "parsable", "delivered", "queue")

    def __init__(self, record, parsable):
        self.record = record
        self.parsable = parsable          # real Parsable or None (lazy-less)
        self.delivered = parsable.delivered if parsable is not None else set()
        self.queue: List[Tuple[Callable, Any]] = []


def _to_string(v) -> Optional[str]:
    if v is None or isinstance(v, str):
        return v
    if isinstance(v, float):
        return _java_double_to_string(v)
    return str(v)


def _to_long(v) -> Optional[int]:
    if v is None or isinstance(v, int):
        return v
    if isinstance(v, str):
        return _parse_java_long(v)
    return Value(v).get_long()


def _to_double(v) -> Optional[float]:
    if v is None:
        return None
    if isinstance(v, str):
        return _parse_java_double(v)
    return float(v)


_CONVERT = {"STRING": _to_string, "LONG": _to_long, "DOUBLE": _to_double}


def _compile_store(parser, key: str, name: str) -> Optional[Route]:
    """Bind one store target's resolved plan into a closure replicating
    Parser.store's inner loop on raw python values."""
    plan = parser._store_plans.get(key)
    if plan is None:
        cache_key: Any = key
        if key not in parser.casts_of_targets:
            cache_key = (key, name)
            plan = parser._store_plans.get(cache_key)
        if plan is None:
            plan = parser._build_store_plan(key, name)
            if plan is None:
                return None
            parser._store_plans[cache_key] = plan
    resolved, casts_to = plan
    bound = tuple(
        (m, a, vtype, _CONVERT[vtype], skip, ne)
        for m, a, vtype, skip, ne in resolved
    )

    generated: Optional[Route] = None
    if os.environ.get(_INTERP_ENV, "") != "1":
        try:
            generated = _generate_store(bound, key, name, casts_to)
        except Exception:  # noqa: BLE001 — codegen must never break compile
            generated = None

    def _interp_store(ctx: _Ctx, v) -> None:
        _run_store(ctx, v, bound, key, name, casts_to)

    store: Route = generated if generated is not None else _interp_store
    store._fl = ("store", key, name, bound, casts_to)  # type: ignore[attr-defined]
    return store


def _emit_store_entry(w: "_CodeWriter", lvl: int, mv: str, entry,
                      key: str, name: str, val: str, casts_var: str) -> None:
    """Emit ONE store entry's guard + setter call + error wrapping — the
    single source of the generated store semantics, shared by the
    standalone store generator and the driver's inline token-stage
    emission (the two must stay byte-identical in guard order and
    failure messages; the differential suite locks both)."""
    method_name, arg_count, vtype, _conv, skip_null, not_empty = entry
    if not_empty and vtype == "STRING":
        w.emit(lvl, 'if out is not None and out != "":')
        lvl += 1
    elif skip_null:
        w.emit(lvl, "if out is not None:")
        lvl += 1
    w.emit(lvl, f"if {mv} is None:")
    w.emit(lvl + 1, f"_rnm(_rec, {method_name!r})")
    w.emit(lvl, "try:")
    if arg_count == 2:
        w.emit(lvl + 1, f"{mv}({name!r}, out)")
    else:
        w.emit(lvl + 1, f"{mv}(out)")
    w.emit(lvl, "except Exception as e:")
    w.emit(
        lvl + 1,
        f"_rse(e, {method_name!r}, {key!r}, {name!r}, {val}, {casts_var})",
    )


def _generate_store(bound, key: str, name: str, casts_to) -> Optional[Route]:
    """Source-generate one store plan: the entry loop unrolled, conv
    dispatch inlined, the setter looked up once.  Same records and same
    failure messages as _run_store (the differential suite compares both);
    emitter-fed values are Any, so convs stay the bound functions."""
    w = _CodeWriter()
    w.emit(0, "def _store(ctx, v):")
    if not bound:
        w.emit(1, f"_rns({key!r}, {name!r}, v)")
    else:
        w.emit(1, "_rec = ctx.record")
        methods = []
        for m, _a, _t, _c, _s, _ne in bound:
            if m not in methods:
                methods.append(m)
        mv = {m: f"_m{j}" for j, m in enumerate(methods)}
        for m in methods:
            w.emit(1, f"{mv[m]} = getattr(_rec, {m!r}, None)")
        cvar = w.bind(casts_to, "ct")
        for entry in bound:
            w.emit(1, f"out = {w.bind(entry[3], 'cv')}(v)")
            _emit_store_entry(w, 1, mv[entry[0]], entry, key, name, "v", cvar)
    exec(compile(w.source(), "<fastline-store>", "exec"), w.ns)  # noqa: S102
    return w.ns["_store"]


def _run_store(ctx: _Ctx, v, bound, key, name, casts_to) -> None:
    record = ctx.record
    called = False
    for method_name, arg_count, vtype, conv, skip_null, not_empty in bound:
        out = conv(v)
        if out is None and skip_null:
            called = True
            continue
        if not_empty and vtype == "STRING" and out == "":
            called = True
            continue
        method = getattr(record, method_name, None)
        if method is None:
            _raise_no_method(record, method_name)
        try:
            if arg_count == 2:
                method(name, out)
            else:
                method(out)
        except Exception as e:  # noqa: BLE001 — mirror the generic wrap
            _raise_setter_error(e, method_name, key, name, v, casts_to)
        called = True
    if not called:
        _raise_no_setter(key, name, v)


def _raise_no_method(record, method_name: str) -> None:
    raise FatalErrorDuringCallOfSetterMethod(
        f"Record {type(record).__name__} has no method {method_name!r}"
    )


def _raise_setter_error(e, method_name, key, name, v, casts_to) -> None:
    raise FatalErrorDuringCallOfSetterMethod(
        f'{e} when calling "{method_name}" for key="{key}" '
        f'name="{name}" value="{v}" casts_to="{casts_to}"'
    ) from e


def _raise_no_setter(key, name, v) -> None:
    raise FatalErrorDuringCallOfSetterMethod(
        f'No setter called for key="{key}" name="{name}" value="{v}"'
    )


def _cache_parsed_field(ctx: _Ctx, ftype: str, complete_name: str, v) -> None:
    """Cache one intermediate on the real Parsable — the read path of the
    generic consumers and the last-chance converter pass."""
    val = v if isinstance(v, Value) else Value(v)
    pf = ParsedField(ftype, complete_name, val)
    ctx.parsable._cache[pf.id] = pf


def _drain_generic(parser, parsable) -> None:
    """Drain intermediates a generic phase enqueued through the real
    Parsable with the generic wave loop (without _run's trailing
    last-chance pass; that runs exactly once per line, like the generic
    engine)."""
    to_be = set(parsable.to_be_parsed)
    while to_be:
        for pf in to_be:
            parsable.set_as_parsed(pf)
            for phase in parser._compiled.get(pf.id, ()):
                phase.instance.dissect(parsable, pf.name)
        to_be = set(parsable.to_be_parsed)


class _Compiler:
    def __init__(self, parser):
        self.parser = parser
        self.route_cache: Dict[Tuple[str, str, str], Route] = {}
        # ids the last-chance converter pass may probe from the cache: those
        # fields must be cached even when no generic phase consumes them.
        self.probe_ids = {
            make_field_id(input_type, nid.partition(":")[2])
            for nid, options in parser._last_chance.items()
            for input_type, _ in options
        }
        # True when any route needs a real Parsable (generic phase,
        # last-chance probe target, or a routing cycle).
        self.any_generic = bool(parser._last_chance)

    # -- routing (the static image of Parsable.add_dissection) -----------

    def route(self, base: str, ftype: str, name: str) -> Route:
        key = (base, ftype, name)
        got = self.route_cache.get(key)
        if got is _IN_PROGRESS:
            # Routing cycle (a dissector chain feeding itself): the generic
            # engine terminates through the Parsable cache — route the
            # cyclic edge generically so it does too.
            self.any_generic = True
            generic = self._generic_route(base, ftype, name)
            self.route_cache[key] = generic
            return generic
        if got is None:
            self.route_cache[key] = _IN_PROGRESS
            compiled = self._compile_route(base, ftype, name)
            if self.route_cache[key] is _IN_PROGRESS:
                self.route_cache[key] = compiled
            got = self.route_cache[key]
        return got

    def _generic_route(self, base: str, ftype: str, name: str) -> Route:
        def generic(ctx: _Ctx, v) -> None:
            ctx.parsable.add_dissection(base, ftype, name, v)
        generic._fl = ("generic", base, ftype, name)  # type: ignore[attr-defined]
        return generic

    def _compile_route(self, base: str, ftype: str, name: str) -> Route:
        parser = self.parser
        complete_name = (
            name if base == ""
            else (base if name == "" else base + "." + name)
        )

        remap_routes: List[Route] = []
        for new_type in parser.type_remappings.get(complete_name, ()):
            if new_type == ftype:
                def bad(ctx, v, _b=base, _t=ftype, _n=name):
                    raise DissectionFailure(
                        "[Type Remapping] Trying to map to the same type "
                        f"(mapping definition bug!): base={_b} type={_t} name={_n}"
                    )
                remap_routes.append(bad)
                continue
            # Remapped delivery never re-applies remappings (the generic
            # path passes _recursion=True) — compile the non-remap tail.
            remap_routes.append(
                self._compile_tail(base, new_type, name, complete_name)
            )
        tail = self._compile_tail(base, ftype, name, complete_name)

        if not remap_routes:
            return tail

        def route(ctx: _Ctx, v) -> None:
            for r in remap_routes:
                r(ctx, v)
            tail(ctx, v)
        route._fl = ("seq", tuple(remap_routes) + (tail,))  # type: ignore[attr-defined]
        return route

    def _compile_tail(
        self, base: str, ftype: str, name: str, complete_name: str
    ) -> Route:
        """The non-remapping part of add_dissection for one static triple."""
        parser = self.parser
        if base == "":
            needed_wildcard = ftype + ":*"
        else:
            needed_wildcard = ftype + ":" + base + ".*"
        needed_name = ftype + ":" + complete_name
        needed = parser.get_needed()

        sinks: List[Route] = []
        fid = make_field_id(ftype, complete_name)
        is_intermediate = complete_name in parser.get_useful_intermediate_fields()
        if is_intermediate:
            phase_runs: List[Route] = []
            for phase in parser._compiled.get(fid, ()):
                phase_runs.append(self._compile_phase(phase, complete_name))
            generic_phases = [
                p for p, r in zip(parser._compiled.get(fid, ()), phase_runs)
                if r is None
            ]
            fast_phases = [r for r in phase_runs if r is not None]
            # Only fields a generic phase consumes or the last-chance pass
            # can probe need the Parsable cache entry (every other cache
            # reader fetches its own input, which the fast phases bypass).
            must_cache = fid in self.probe_ids or bool(generic_phases)
            if must_cache:
                self.any_generic = True

            generic_runs = [
                (lambda ctx2, _v, _p=p: _p.instance.dissect(
                    ctx2.parsable, complete_name))
                for p in generic_phases
            ]

            def intermediate(ctx: _Ctx, v) -> None:
                if must_cache:
                    # The generic consumers (and the last-chance pass) read
                    # the field from the Parsable cache, exactly like the
                    # generic engine caches useful intermediates.
                    _cache_parsed_field(ctx, ftype, complete_name, v)
                for r in fast_phases:
                    ctx.queue.append((r, v))
                for g in generic_runs:
                    ctx.queue.append((g, v))
            intermediate._fl = (  # type: ignore[attr-defined]
                "intermediate", must_cache, ftype, complete_name,
                tuple(fast_phases), tuple(generic_runs),
            )
            sinks.append(intermediate)

        if needed_name in needed:
            store = _compile_store(parser, needed_name, needed_name)
            if store is not None:
                def needed_sink(ctx: _Ctx, v, _s=store) -> None:
                    ctx.delivered.add(needed_name)
                    _s(ctx, v)
                needed_sink._fl = ("needed", needed_name, store)  # type: ignore[attr-defined]
                sinks.append(needed_sink)
        if needed_wildcard in needed:
            store = _compile_store(parser, needed_wildcard, needed_name)
            if store is not None:
                sinks.append(store)

        if not sinks:
            def noop(ctx: _Ctx, v) -> None:
                return
            noop._fl = ("noop",)  # type: ignore[attr-defined]
            return noop
        if len(sinks) == 1:
            return sinks[0]

        def multi(ctx: _Ctx, v) -> None:
            for s in sinks:
                s(ctx, v)
        multi._fl = ("seq", tuple(sinks))  # type: ignore[attr-defined]
        return multi

    # -- value-level emitters for the hot sub-dissectors -----------------

    def _compile_phase(self, phase, input_name: str) -> Optional[Route]:
        """A value-level replay of one compiled phase, or None when the
        dissector must run generically (against a real Parsable)."""
        from ..dissectors.firstline import (
            HttpFirstLineDissector,
            HttpFirstLineProtocolDissector,
        )
        from ..dissectors.timestamp import TimeStampDissector
        from ..dissectors.translate import (
            ConvertCLFIntoNumber,
            ConvertMillisecondsIntoMicroseconds,
            ConvertNumberIntoCLF,
            ConvertSecondsWithMillisStringDissector,
        )

        from ..geoip.dissectors import (
            GeoIPASNDissector,
            GeoIPCityDissector,
            GeoIPCountryDissector,
            GeoIPISPDissector,
        )

        from ..dissectors.strftime_stamp import StrfTimeStampDissector

        inst = phase.instance
        if isinstance(inst, TimeStampDissector):
            return self._compile_timestamp(inst, input_name)
        if isinstance(inst, StrfTimeStampDissector):
            # The strftime wrapper delegates dissect/prepare to its
            # embedded TimeStampDissector (strftime_stamp.py:210-213), so
            # the embedded instance carries the layout/locale/wanted set
            # the timestamp emitter compiles from.
            return self._compile_timestamp(inst.timestamp_dissector,
                                           input_name)
        # EXACT types only: AbstractGeoIPDissector is an extension point;
        # a subclass overriding dissect()/extract() (or touching Parsable
        # methods beyond add_dissection) must keep the generic path.
        if type(inst) in (GeoIPCountryDissector, GeoIPCityDissector,
                          GeoIPASNDissector, GeoIPISPDissector):
            return self._compile_geoip(inst, input_name)
        from ..dissectors.uri import HttpUriDissector

        if type(inst) is HttpUriDissector:
            # EXACT type: dissect uses only get_parsable_field +
            # add_dissection with static names (uri.py:217-280); a
            # subclass overriding dissect keeps the generic path.
            return self._compile_value_shim(inst, input_name)
        if isinstance(inst, HttpFirstLineDissector):
            return self._compile_firstline(inst, input_name)
        if isinstance(inst, HttpFirstLineProtocolDissector):
            return self._compile_protocol(inst, input_name)
        if isinstance(inst, ConvertCLFIntoNumber):
            out = self.route(input_name, inst.output_type, "")

            def clf_num(ctx: _Ctx, v) -> None:
                s = _to_string(v)
                out(ctx, 0 if (s is None or s == "-") else v)
            return clf_num
        if isinstance(inst, ConvertNumberIntoCLF):
            out = self.route(input_name, inst.output_type, "")

            def num_clf(ctx: _Ctx, v) -> None:
                out(ctx, None if _to_string(v) == "0" else v)
            return num_clf
        if isinstance(inst, ConvertMillisecondsIntoMicroseconds):
            out = self.route(input_name, inst.output_type, "")

            def ms_us(ctx: _Ctx, v) -> None:
                out(ctx, _to_long(v) * 1000)
            return ms_us
        if isinstance(inst, ConvertSecondsWithMillisStringDissector):
            out = self.route(input_name, inst.output_type, "")

            def secms(ctx: _Ctx, v) -> None:
                seconds_str, _, millis_str = _to_string(v).partition(".")
                out(ctx, int(seconds_str) * 1000 + int(millis_str))
            return secms
        return None

    def _compile_value_shim(self, inst, input_name: str) -> Route:
        """Value-level replay for dissectors whose ``dissect`` touches the
        Parsable only through get_parsable_field + add_dissection with
        STATIC output names (contract: outputs ⊆ get_possible_output).
        Twin of _compile_geoip's shim (that one feeds ``extract`` with
        looked-up data instead of wrapping a value) — keep their route
        pre-resolution and dispatch in sync.
        The dissector's own byte-level code runs unmodified — semantics
        stay single-sourced — but every emitted value dispatches through
        precompiled routes (the routing was most of the per-line cost)."""
        compiler = self

        # Resolve every possible output's route at COMPILE time; the
        # runtime route() probes below are then memo hits.
        for out in inst.get_possible_output():
            ot, _, oname = out.partition(":")
            compiler.route(input_name, ot, oname)

        class _ValueShim:
            __slots__ = ("ctx", "value")

            def __init__(self, ctx, value):
                self.ctx = ctx
                self.value = value

            def get_parsable_field(self, ftype, name):
                return ParsedField(ftype, name, self.value)

            def add_dissection(self, base, ftype, name, value):
                compiler.route(base, ftype, name)(self.ctx, value)

        def shim_emit(ctx: _Ctx, v) -> None:
            inst.dissect(_ValueShim(ctx, v), input_name)
        return shim_emit

    def _compile_geoip(self, inst, input_name: str) -> Route:
        """Value-level GeoIP replay: the per-line work (IP parse, mmdb
        lookup, extract) reuses the dissector's own code — semantics stay
        single-sourced — but `extract`'s add_dissection calls dispatch
        through precompiled routes instead of a real Parsable (the
        routing was ~the whole non-lookup cost in the generic engine)."""
        import ipaddress

        compiler = self

        # Resolve every possible output's route at COMPILE time so first-
        # line latency doesn't pay route compilation (route() memoizes;
        # the shim then pays one dict probe per produced output).
        for out in inst.get_possible_output():
            ot, _, oname = out.partition(":")
            compiler.route(input_name, ot, oname)

        class _GeoShim:
            __slots__ = ("ctx",)

            def __init__(self, ctx):
                self.ctx = ctx

            def add_dissection(self, base, ftype, name, value):
                compiler.route(base, ftype, name)(self.ctx, value)

        # String-keyed memo over the whole parse+lookup: repeated client
        # IPs (the norm in real corpora) cost one dict probe per line —
        # even ipaddress parsing is skipped.  Unparseable strings cache
        # as misses too.  Same crude clear-when-full bound as the reader.
        memo: Dict[str, Any] = {}
        _MISS = object()

        def geo_emit(ctx: _Ctx, v) -> None:
            s = _to_string(v)
            if not s:
                return
            data = memo.get(s, _MISS)
            if data is _MISS:
                reader = inst._reader
                try:
                    addr = ipaddress.ip_address(s)
                except ValueError:
                    data = None
                else:
                    data = reader.lookup_address(addr) if reader else None
                if len(memo) >= 65536:
                    memo.clear()
                memo[s] = data
            if data is None:
                return
            inst.extract(_GeoShim(ctx), input_name, data)

        return geo_emit

    def _compile_timestamp(self, inst, input_name: str) -> Route:
        from .exceptions import DissectionFailure as DF
        from ..dissectors.timelayout import TimestampParseError, week_based_fields
        from ..dissectors.timestamp import _LOCAL_FIELDS

        layout = inst.get_layout()
        locale = inst.locale
        w = inst.wanted

        emits: List[Tuple[bool, Callable, Route]] = []  # (is_utc, compute, route)
        if "timezone" in w:
            emits.append((False, lambda ts: ts.zone_display_name(),
                          self.route(input_name, "TIME.TIMEZONE", "timezone")))
        if "epoch" in w:
            emits.append((False, lambda ts: ts.epoch_millis,
                          self.route(input_name, "TIME.EPOCH", "epoch")))
        computes = {
            "day": lambda ts: ts.day,
            "monthname": lambda ts: locale.months_full[ts.month - 1],
            "month": lambda ts: ts.month,
            "year": lambda ts: ts.year,
            "hour": lambda ts: ts.hour,
            "minute": lambda ts: ts.minute,
            "second": lambda ts: ts.second,
            "millisecond": lambda ts: ts.nano // 1_000_000,
            "microsecond": lambda ts: ts.nano // 1_000,
            "nanosecond": lambda ts: ts.nano,
            "date": lambda ts: ts.date_str(),
            "time": lambda ts: ts.time_str(),
        }
        for suffix, is_utc in (("", False), ("_utc", True)):
            for fname, ftype, _ in _LOCAL_FIELDS:
                if fname + suffix not in w:
                    continue
                r = self.route(input_name, ftype, fname + suffix)
                if fname == "weekofweekyear":
                    if is_utc:
                        compute = lambda ts: ts.iso_week()  # noqa: E731
                    else:
                        compute = lambda ts: week_based_fields(  # noqa: E731
                            ts.year, ts.month, ts.day,
                            locale.week_first_day, locale.week_min_days)[1]
                elif fname == "weekyear":
                    if is_utc:
                        compute = lambda ts: ts.iso_weekyear()  # noqa: E731
                    else:
                        compute = lambda ts: week_based_fields(  # noqa: E731
                            ts.year, ts.month, ts.day,
                            locale.week_first_day, locale.week_min_days)[0]
                else:
                    compute = computes[fname]
                emits.append((is_utc, compute, r))
        any_utc = any(is_utc for is_utc, _, _ in emits)

        def ts_emit(ctx: _Ctx, v) -> None:
            value = _to_string(v)
            if value is None or value == "":
                return
            try:
                ts = layout.parse(value)
            except TimestampParseError as e:
                raise DF(str(e)) from e
            except (ValueError, IndexError) as e:
                raise DF(f"Unable to parse timestamp {value!r}: {e}") from e
            utc = ts.utc_fields() if any_utc else None
            for is_utc, compute, r in emits:
                r(ctx, compute(utc if is_utc else ts))
        return ts_emit

    def _compile_firstline(self, inst, input_name: str) -> Route:
        req = inst.requested
        routes = {
            "method": self.route(input_name, "HTTP.METHOD", "method"),
            "uri": self.route(input_name, "HTTP.URI", "uri"),
            "protocol": self.route(input_name, "HTTP.PROTOCOL_VERSION",
                                   "protocol"),
        }
        splitter = inst._SPLITTER
        too_long = inst._TOO_LONG_SPLITTER

        def fl_emit(ctx: _Ctx, v) -> None:
            value = _to_string(v)
            if value is None or value == "" or value == "-":
                return
            m = splitter.search(value)
            if m is not None:
                if "method" in req:
                    routes["method"](ctx, m.group(1))
                if "uri" in req:
                    routes["uri"](ctx, m.group(2))
                if "protocol" in req:
                    routes["protocol"](ctx, m.group(3))
                return
            m = too_long.search(value)
            if m is not None:
                if "method" in req:
                    routes["method"](ctx, m.group(1))
                if "uri" in req:
                    routes["uri"](ctx, m.group(2))
                routes["protocol"](ctx, None)
        return fl_emit

    def _compile_protocol(self, inst, input_name: str) -> Route:
        req = inst.requested
        r_proto = self.route(input_name, "HTTP.PROTOCOL", "")
        r_ver = self.route(input_name, "HTTP.PROTOCOL.VERSION", "version")

        def proto_emit(ctx: _Ctx, v) -> None:
            value = _to_string(v)
            if value is None or value == "" or value == "-":
                return
            parts = value.split("/", 1)
            if len(parts) == 2:
                if "" in req:
                    r_proto(ctx, parts[0])
                if "version" in req:
                    r_ver(ctx, parts[1])
                return
            r_proto(ctx, None)
            r_ver(ctx, None)
        return proto_emit


class _FormatProgram:
    """One LogFormat's compiled stage-1: regex match -> token routes."""

    __slots__ = ("tf", "token_routes", "apache_decode")

    def __init__(self, tf, token_routes):
        self.tf = tf
        self.token_routes = token_routes
        # The Apache decode (decode_extracted_apache_value) is value-only
        # — inline it to skip two function calls per token; other dialects
        # keep the method call.
        from ..httpd.apache import ApacheHttpdLogFormatDissector

        self.apache_decode = type(tf) is ApacheHttpdLogFormatDissector

    def run(self, ctx: _Ctx, line: str) -> None:
        tf = self.tf
        if not tf._usable:
            raise DissectionFailure("Dissector in unusable state")
        m = tf._pattern.search(line) if line is not None else None
        if m is None:
            raise DissectionFailure(
                "The input line does not match the specified log format."
                f"Line     : {line}\n"
                f"LogFormat: {tf.log_format}\n"
                f"RegEx    : {tf._regex}"
            )
        groups = m.groups()
        if self.apache_decode:
            from ..dissectors.utils import decode_apache_httpd_log_value

            for matched, fields in zip(groups, self.token_routes):
                if matched == "-":
                    matched = None
                elif matched and (
                    matched == "request.firstline"
                    or matched.startswith(
                        ("request.header.", "response.header.")
                    )
                ):
                    # Faithful upstream quirk: the reference compares the
                    # VALUE against these names (utils_apache.py).
                    matched = decode_apache_httpd_log_value(matched)
                for _fname, route in fields:
                    route(ctx, matched)
            return
        decode = tf.decode_extracted_value
        for i, fields in enumerate(self.token_routes, start=1):
            matched = groups[i - 1]
            for fname, route in fields:
                route(ctx, decode(fname, matched))


class FastLineEngine:
    """Compiled replay of Parser.parse for HttpdLogFormat-rooted parsers."""

    # Set by generate_fastline_code when the exec'd driver is attached
    # (the instance attribute `parse` then shadows the interpreted method).
    codegen_active = False
    generated_source: Optional[str] = None

    def interpreted_parse(self, line: str, record: Any) -> Any:
        """The interpreted driver, reachable even with codegen attached —
        the referee the codegen differential suite compares against."""
        return FastLineEngine.parse(self, line, record)

    def __init__(self, parser, programs: List[_FormatProgram],
                 needs_parsable: bool, cache_root: bool = False):
        self.parser = parser
        self.programs = programs
        self.needs_parsable = needs_parsable
        # Cache the root field only when the last-chance pass could probe
        # it (nothing else reads it on the compiled path).
        self.cache_root = cache_root
        # Per-engine outcome tallies, plain ints (GIL-atomic enough for
        # counters; NO registry/lock work on the per-line path).  The batch
        # pipeline folds deltas into the metrics registry per batch
        # (TpuBatchParser._fold_oracle_engine_tally): parsed / rejected
        # line outcomes plus format_fallback — lines the primary format
        # rejected that a later registered format accepted (the columnar
        # "Switched to LogFormat" signal at engine level).
        self.tally = {"parsed": 0, "rejected": 0, "format_fallback": 0}

    def parse(self, line: str, record: Any) -> Any:
        parser = self.parser
        parsable = None
        if self.needs_parsable:
            parsable = parser.create_parsable(record)
            if self.cache_root:
                parsable.set_root_dissection(parser.root_type, line)
                parsable.to_be_parsed.clear()
        ctx = _Ctx(record, parsable)
        programs = self.programs
        tally = self.tally
        try:
            programs[0].run(ctx, line)
        except DissectionFailure:
            # Multi-format fallback: on failure retry EVERY format in
            # registration order (HttpdLogFormatDissector.java:174-204;
            # stateless mode, so priority order every line).  Partial
            # deliveries before the failure stay, like the generic path.
            if len(programs) <= 1:
                tally["rejected"] += 1
                raise
            for prog in programs:
                try:
                    prog.run(ctx, line)
                    tally["format_fallback"] += 1
                    break
                except DissectionFailure:
                    continue
            else:
                tally["rejected"] += 1
                raise
        # Stage 2: sub-dissector waves in FIFO order (the generic worklist
        # equivalent).  Emitters may enqueue further work (firstline -> URI).
        queue = ctx.queue
        i = 0
        while i < len(queue):
            fn, v = queue[i]
            i += 1
            fn(ctx, v)
            if parsable is not None and parsable.to_be_parsed:
                # A generic phase enqueued new intermediates through the
                # real Parsable — drain them with the generic wave loop.
                _drain_generic(parser, parsable)
        if parsable is not None:
            parser._last_chance_converters(parsable)
        tally["parsed"] += 1
        return record

    def parse_many(self, lines, record_factory) -> List[Optional[Any]]:
        """Batched parse with amortized per-call setup: one engine fetch,
        hoisted locals, one record per line.  Returns the record for each
        parsed line, None for each DissectionFailure, and an
        :class:`~logparser_tpu.core.exceptions.OracleEngineError` marker
        where the engine itself raised — one broken line costs itself a
        reasoned reject, never the whole rescue batch (matching
        ``Parser.parse_many``)."""
        from .exceptions import OracleEngineError

        parse = self.parse
        out: List[Optional[Any]] = []
        append = out.append
        for line in lines:
            rec = record_factory()
            try:
                parse(line, rec)
                append(rec)
            except DissectionFailure:
                append(None)
            except Exception as e:  # noqa: BLE001 — engine fault, per line
                append(OracleEngineError(f"{type(e).__name__}: {e}"))
        return out


# ---------------------------------------------------------------------------
# Store-program source generation.
#
# The interpreted engine above dispatches each token capture through nested
# route closures: a per-token list walk, a per-sink loop, a per-setter-entry
# loop with conv dispatch, and explicit noop calls for unrequested outputs.
# Per line that interpretation overhead is ~35-40% of the oracle's wall time
# (profiled: store loop + needed_sink + noop + _FormatProgram.run dispatch).
# This backend compiles the SAME route structure (walked via the ``_fl``
# metadata each closure carries) into one exec'd straight-line function per
# format — noop routes vanish, sink/entry loops unroll, value conversions
# inline (token captures are str|None by construction on the Apache dialect),
# and record setters are looked up once per line instead of once per value —
# plus a flat per-line driver replacing FastLineEngine.parse.
#
# Semantics contract: byte-identical records and failure messages vs the
# interpreted engine (locked by the differential suite in
# tests/test_fastline_codegen.py).  Sub-dissector emitters stay the compiled
# closures they already were; only the routing/storing interpretation is
# generated away.  LOGPARSER_TPU_FASTLINE_INTERP=1 disables generation.
# ---------------------------------------------------------------------------


def _raise_unusable() -> None:
    raise DissectionFailure("Dissector in unusable state")


def _make_format_miss(tf):
    def miss(line):
        raise DissectionFailure(
            "The input line does not match the specified log format."
            f"Line     : {line}\n"
            f"LogFormat: {tf.log_format}\n"
            f"RegEx    : {tf._regex}"
        )
    return miss


class _CodeWriter:
    """Source accumulator + exec namespace for one generated engine."""

    def __init__(self):
        self.lines: List[str] = []
        self.ns: Dict[str, Any] = {
            "_DF": DissectionFailure,
            "_Ctx": _Ctx,
            "_rnm": _raise_no_method,
            "_rse": _raise_setter_error,
            "_rns": _raise_no_setter,
            "_cpf": _cache_parsed_field,
            "_pjl": _parse_java_long,
            "_pjd": _parse_java_double,
        }
        self._n = 0
        self._bound: Dict[int, str] = {}

    def bind(self, obj, prefix: str = "o") -> str:
        got = self._bound.get(id(obj))
        if got is not None:
            return got
        name = f"_{prefix}{self._n}"
        self._n += 1
        self.ns[name] = obj
        self._bound[id(obj)] = name
        return name

    def emit(self, indent: int, line: str) -> None:
        self.lines.append("    " * indent + line)

    def source(self) -> str:
        return "\n".join(self.lines) + "\n"


def _walk_routes(route, visit) -> None:
    """Depth-first walk over a route's ``_fl`` structure."""
    meta = getattr(route, "_fl", None)
    visit(route, meta)
    if meta is None:
        return
    if meta[0] == "seq":
        for part in meta[1]:
            _walk_routes(part, visit)
    elif meta[0] == "needed":
        _walk_routes(meta[2], visit)


class _EngineCodegen:
    def __init__(self, engine: FastLineEngine):
        self.engine = engine
        self.w = _CodeWriter()

    # -- structure scan --------------------------------------------------

    def _scan(self):
        """Which hoists the generated run functions need: store method
        names, queue use, delivered tracking, generic add_dissection."""
        methods: List[str] = []
        flags = {"queue": False, "delivered": False}

        def visit(route, meta):
            if meta is None:
                return
            kind = meta[0]
            if kind == "store":
                for m, _a, _t, _c, _s, _ne in meta[3]:
                    if m not in methods:
                        methods.append(m)
            elif kind == "needed":
                flags["delivered"] = True
            elif kind == "intermediate":
                flags["queue"] = True

        for prog in self.engine.programs:
            for fields in prog.token_routes:
                for _fname, route in fields:
                    _walk_routes(route, visit)
        return methods, flags

    # -- store emission --------------------------------------------------

    def _emit_store(self, indent: int, meta, val: str, val_is_str: bool,
                    method_vars: Dict[str, str]) -> None:
        _kind, key, name, bound, casts_to = meta
        w = self.w
        if not bound:
            w.emit(indent, f"_rns({key!r}, {name!r}, {val})")
            return
        casts_var = w.bind(casts_to, "ct")
        for entry in bound:
            method_name, _arg_count, vtype, conv, _skip, _ne = entry
            if val_is_str and vtype == "STRING":
                # Token captures are str|None: _to_string is identity.
                out = val
            elif val_is_str and vtype == "LONG":
                out = f"(_pjl({val}) if {val} is not None else None)"
            elif val_is_str and vtype == "DOUBLE":
                out = f"(_pjd({val}) if {val} is not None else None)"
            else:
                out = f"{w.bind(conv, 'cv')}({val})"
            w.emit(indent, f"out = {out}")
            _emit_store_entry(w, indent, method_vars[method_name], entry,
                              key, name, val, casts_var)

    # -- route emission --------------------------------------------------

    def _route_is_noop(self, route) -> bool:
        meta = getattr(route, "_fl", None)
        if meta is None:
            return False
        if meta[0] == "noop":
            return True
        if meta[0] == "seq":
            return all(self._route_is_noop(p) for p in meta[1])
        return False

    def _emit_route(self, indent: int, route, val: str, val_is_str: bool,
                    method_vars: Dict[str, str],
                    track_delivered: bool) -> None:
        w = self.w
        meta = getattr(route, "_fl", None)
        if meta is None:
            w.emit(indent, f"{w.bind(route, 'r')}(ctx, {val})")
            return
        kind = meta[0]
        if kind == "noop":
            return
        if kind == "seq":
            for part in meta[1]:
                self._emit_route(indent, part, val, val_is_str,
                                 method_vars, track_delivered)
            return
        if kind == "needed":
            if track_delivered:
                w.emit(indent, f"_dlv.add({meta[1]!r})")
            self._emit_route(indent, meta[2], val, val_is_str,
                             method_vars, track_delivered)
            return
        if kind == "store":
            self._emit_store(indent, meta, val, val_is_str, method_vars)
            return
        if kind == "intermediate":
            _k, must_cache, ftype, cname, fast_phases, generic_runs = meta
            if must_cache:
                w.emit(indent, f"_cpf(ctx, {ftype!r}, {cname!r}, {val})")
            for p in fast_phases:
                w.emit(indent, f"_q.append(({w.bind(p, 'em')}, {val}))")
            for g in generic_runs:
                w.emit(indent, f"_q.append(({w.bind(g, 'gn')}, {val}))")
            return
        if kind == "generic":
            _k, base, ftype, name = meta
            w.emit(
                indent,
                f"ctx.parsable.add_dissection({base!r}, {ftype!r}, "
                f"{name!r}, {val})",
            )
            return
        # Unknown future kind: call the closure (never wrong, just slower).
        w.emit(indent, f"{w.bind(route, 'r')}(ctx, {val})")

    # -- per-format run function ----------------------------------------

    def _emit_program(self, k: int, prog: _FormatProgram,
                      methods: List[str], flags) -> str:
        from ..dissectors.utils import decode_apache_httpd_log_value

        w = self.w
        track_delivered = self.engine.needs_parsable
        fn = f"_fmt_run_{k}"
        tf_var = w.bind(prog.tf, "tf")
        pat_var = w.bind(prog.tf._pattern.search, "pat")
        miss_var = w.bind(_make_format_miss(prog.tf), "miss")
        method_vars = {m: f"_m{j}" for j, m in enumerate(methods)}

        w.emit(0, f"def {fn}(ctx, line):")
        w.emit(1, f"if not {tf_var}._usable:")
        w.emit(2, "_raise_unusable()")
        w.emit(1, f"m = {pat_var}(line) if line is not None else None")
        w.emit(1, "if m is None:")
        w.emit(2, f"{miss_var}(line)")
        w.emit(1, "g = m.groups()")
        w.emit(1, "_rec = ctx.record")
        if flags["queue"]:
            w.emit(1, "_q = ctx.queue")
        if track_delivered and flags["delivered"]:
            w.emit(1, "_dlv = ctx.delivered")
        for m in methods:
            w.emit(1, f"{method_vars[m]} = getattr(_rec, {m!r}, None)")
        w.ns["_raise_unusable"] = _raise_unusable

        emitted_any = False
        if prog.apache_decode:
            dec_var = w.bind(decode_apache_httpd_log_value, "apdec")
            hdrs = ("request.header.", "response.header.")
            hdrs_var = w.bind(hdrs, "hdr")
            for i, fields in enumerate(prog.token_routes):
                live = [
                    (fname, r) for fname, r in fields
                    if not self._route_is_noop(r)
                ]
                if not live:
                    continue
                emitted_any = True
                w.emit(1, f"v = g[{i}]")
                w.emit(1, 'if v == "-":')
                w.emit(2, "v = None")
                # Faithful upstream quirk: the reference compares the
                # VALUE against these names (utils_apache.py).
                w.emit(1, 'elif v and (v == "request.firstline" '
                          f"or v.startswith({hdrs_var})):")
                w.emit(2, f"v = {dec_var}(v)")
                for _fname, route in live:
                    self._emit_route(1, route, "v", True,
                                     method_vars, track_delivered)
        else:
            dec_var = w.bind(prog.tf.decode_extracted_value, "dec")
            for i, fields in enumerate(prog.token_routes):
                live = [
                    (fname, r) for fname, r in fields
                    if not self._route_is_noop(r)
                ]
                if not live:
                    continue
                emitted_any = True
                w.emit(1, f"v = g[{i}]")
                for j, (fname, route) in enumerate(live):
                    # Dialect decode runs per (name, capture) pair, like
                    # the interpreted loop; its output type is dialect-
                    # defined, so conversions stay the bound convs.
                    w.emit(1, f"d{j} = {dec_var}({fname!r}, v)")
                    self._emit_route(1, route, f"d{j}", False,
                                     method_vars, track_delivered)
        if not emitted_any:
            w.emit(1, "pass")
        w.emit(0, "")
        return fn

    # -- the per-line driver ---------------------------------------------

    def generate(self):
        engine = self.engine
        w = self.w
        methods, flags = self._scan()
        run_fns = [
            self._emit_program(k, prog, methods, flags)
            for k, prog in enumerate(engine.programs)
        ]

        parser = engine.parser
        w.ns["_tally"] = engine.tally
        w.emit(0, "def _parse(line, record):")
        if engine.needs_parsable:
            mk = w.bind(parser.create_parsable, "mkp")
            w.emit(1, f"parsable = {mk}(record)")
            if engine.cache_root:
                rt = w.bind(parser.root_type, "rt")
                w.emit(1, f"parsable.set_root_dissection({rt}, line)")
                w.emit(1, "parsable.to_be_parsed.clear()")
            w.emit(1, "ctx = _Ctx(record, parsable)")
        else:
            w.emit(1, "ctx = _Ctx(record, None)")
        w.emit(1, "try:")
        w.emit(2, f"{run_fns[0]}(ctx, line)")
        w.emit(1, "except _DF:")
        if len(run_fns) <= 1:
            w.emit(2, "_tally['rejected'] += 1")
            w.emit(2, "raise")
        else:
            # Multi-format fallback: on failure retry EVERY format in
            # registration order (stateless mode); partial deliveries
            # before the failure stay, like the interpreted path.
            w.emit(2, f"for _run in ({', '.join(run_fns)},):")
            w.emit(3, "try:")
            w.emit(4, "_run(ctx, line)")
            w.emit(4, "_tally['format_fallback'] += 1")
            w.emit(4, "break")
            w.emit(3, "except _DF:")
            w.emit(4, "continue")
            w.emit(2, "else:")
            w.emit(3, "_tally['rejected'] += 1")
            w.emit(3, "raise")
        w.emit(1, "q = ctx.queue")
        w.emit(1, "i = 0")
        w.emit(1, "while i < len(q):")
        w.emit(2, "fn, v = q[i]")
        w.emit(2, "i += 1")
        w.emit(2, "fn(ctx, v)")
        if engine.needs_parsable:
            drain = w.bind(_drain_generic, "drain")
            pvar = w.bind(parser, "parser")
            w.emit(2, "if parsable.to_be_parsed:")
            w.emit(3, f"{drain}({pvar}, parsable)")
            lc = w.bind(parser._last_chance_converters, "lc")
            w.emit(1, f"{lc}(parsable)")
        w.emit(1, "_tally['parsed'] += 1")
        w.emit(1, "return record")
        w.emit(0, "")

        source = w.source()
        code = compile(source, "<fastline-codegen>", "exec")
        exec(code, w.ns)  # noqa: S102 — our own generated source
        return w.ns["_parse"], source


def generate_fastline_code(engine: FastLineEngine) -> bool:
    """Attach a generated per-line driver to ``engine`` (see the section
    comment above).  Returns True when generation succeeded and
    ``engine.parse`` now runs generated code; on any failure the
    interpreted engine is left untouched."""
    gen = _EngineCodegen(engine)
    parse, source = gen.generate()
    engine.parse = parse  # type: ignore[method-assign] — instance attr wins
    engine.generated_source = source
    engine.codegen_active = True
    return True


def compile_fastline(parser) -> Optional[FastLineEngine]:
    """Compile the assembled parser into a FastLineEngine, or None when a
    construct the compiled path cannot faithfully replay is present."""
    from ..httpd.format_dissector import HttpdLogFormatDissector

    if parser.root_type is None:
        return None
    root_id = make_field_id(parser.root_type, "")
    root_phases = parser._compiled.get(root_id, ())
    if len(root_phases) != 1:
        return None
    root = root_phases[0].instance
    if not isinstance(root, HttpdLogFormatDissector):
        return None
    if not root.stateless:
        # Stateful active-format switching is stream-history-dependent;
        # the compiled replay only models the deterministic stateless mode.
        return None
    if not root.dissectors:
        return None

    compiler = _Compiler(parser)
    programs: List[_FormatProgram] = []
    for tf in root.dissectors:
        if not getattr(tf, "_usable", False):
            return None
        token_routes = []
        for token in tf._used_tokens:
            fields = []
            for f in token.output_fields:
                fields.append((f.name, compiler.route("", f.type, f.name)))
            token_routes.append(fields)
        programs.append(_FormatProgram(tf, token_routes))

    # Generic phases, last-chance probes and routing cycles need a real
    # Parsable per line; the compiler recorded whether any route does.
    engine = FastLineEngine(
        parser, programs,
        needs_parsable=compiler.any_generic,
        cache_root=root_id in compiler.probe_ids,
    )
    if os.environ.get(_INTERP_ENV, "") != "1":
        try:
            generate_fastline_code(engine)
        except Exception:  # noqa: BLE001 — codegen must never break parsing
            import logging

            logging.getLogger(__name__).warning(
                "fastline codegen failed; keeping the interpreted engine",
                exc_info=True,
            )
    return engine
