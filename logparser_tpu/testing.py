"""Declarative test fixtures: DissectorTester + canonical dummy dissectors.

Rebuild of the reference's highest-leverage test asset
(parser-core/src/test/java/nl/basjes/parse/core/test/DissectorTester.java):
a fluent harness ``DissectorTester.create().with_dissector(d).with_input(s)
.expect("TYPE:name", value).check_expectations()``.  Every check also proves
serializability by pickling + unpickling the assembled parser first
(DissectorTester.java:257-264 does the same with SerializationUtils.clone).
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional, Tuple, Union

from .core import (
    Dissector,
    Parser,
    SimpleDissector,
    STRING_ONLY,
    STRING_OR_DOUBLE,
    STRING_OR_LONG,
    STRING_OR_LONG_OR_DOUBLE,
)
from .core.fields import ParsedField
from .core.parsable import Parsable


class TestRecord:
    """Record that captures every delivered value keyed by full field id."""

    __test__ = False  # not a pytest test class

    def __init__(self) -> None:
        self.string_values: Dict[str, Optional[str]] = {}
        self.long_values: Dict[str, Optional[int]] = {}
        self.double_values: Dict[str, Optional[float]] = {}

    def set_string_value(self, name: str, value: str) -> None:
        self.string_values[name] = value

    def set_long_value(self, name: str, value: int) -> None:
        self.long_values[name] = value

    def set_double_value(self, name: str, value: float) -> None:
        self.double_values[name] = value


class UltimateDummyDissector(SimpleDissector):
    """Canonical fake dissector covering every output type family.

    Reference: parser-core/src/test/.../UltimateDummyDissector.java:30-46.
    """

    def __init__(self, input_type: str = "INPUT"):
        super().__init__(
            input_type,
            {
                "ANY:any": STRING_OR_LONG_OR_DOUBLE,
                "STRING:string": STRING_ONLY,
                "INT:int": STRING_OR_LONG,
                "LONG:long": STRING_OR_LONG,
                "FLOAT:float": STRING_OR_DOUBLE,
                "DOUBLE:double": STRING_OR_DOUBLE,
            },
        )

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        self.set_input_type(settings)
        return True


class NormalValuesDissector(UltimateDummyDissector):
    def dissect_field(self, parsable: Parsable, input_name: str, pf: ParsedField) -> None:
        parsable.add_dissection(input_name, "ANY", "any", "42")
        parsable.add_dissection(input_name, "STRING", "string", "FortyTwo")
        parsable.add_dissection(input_name, "INT", "int", 42)
        parsable.add_dissection(input_name, "LONG", "long", 42)
        parsable.add_dissection(input_name, "FLOAT", "float", 42.0)
        parsable.add_dissection(input_name, "DOUBLE", "double", 42.0)


class EmptyValuesDissector(UltimateDummyDissector):
    def dissect_field(self, parsable: Parsable, input_name: str, pf: ParsedField) -> None:
        for ftype, name in [
            ("ANY", "any"),
            ("STRING", "string"),
            ("INT", "int"),
            ("LONG", "long"),
            ("FLOAT", "float"),
            ("DOUBLE", "double"),
        ]:
            parsable.add_dissection(input_name, ftype, name, "")


class NullValuesDissector(UltimateDummyDissector):
    def dissect_field(self, parsable: Parsable, input_name: str, pf: ParsedField) -> None:
        for ftype, name in [
            ("ANY", "any"),
            ("STRING", "string"),
            ("INT", "int"),
            ("LONG", "long"),
            ("FLOAT", "float"),
            ("DOUBLE", "double"),
        ]:
            parsable.add_dissection(input_name, ftype, name, None)


class _PrefixRootDissector(Dissector):
    """Re-emits the root input under a dotted prefix so dissectors whose input
    sits below the root (e.g. wildcard producers) can be tested in isolation.

    Reference: DissectorTester's DummyDissector root wrapper
    (DissectorTester.java:76-86) working around the wildcard-at-root limitation.
    """

    def __init__(self, root_type: str = "ROOTINPUT", prefix: str = "prefix",
                 target_type: str = "INPUT"):
        self.root_type = root_type
        self.prefix = prefix
        self.target_type = target_type

    def get_input_type(self) -> str:
        return self.root_type

    def get_possible_output(self) -> List[str]:
        return [f"{self.target_type}:{self.prefix}"]

    def get_new_instance(self) -> "Dissector":
        return _PrefixRootDissector(self.root_type, self.prefix, self.target_type)

    def dissect(self, parsable: Parsable, input_name: str) -> None:
        pf = parsable.get_parsable_field(self.root_type, input_name)
        if pf is not None:
            parsable.add_dissection(input_name, self.target_type, self.prefix, pf.value)


Expectation = Tuple[str, str, Any]  # (kind, field, expected)


class DissectorTester:
    """Fluent declarative dissector test harness."""

    def __init__(self) -> None:
        self.inputs: List[str] = []
        self.dissectors: List[Dissector] = []
        self.expectations: List[Expectation] = []
        self.possible_expectations: List[str] = []
        self.absent_possible: List[str] = []
        self.path_prefix: Optional[str] = None
        self._verbose = False

    @classmethod
    def create(cls) -> "DissectorTester":
        return cls()

    def with_dissector(self, dissector: Dissector) -> "DissectorTester":
        self.dissectors.append(dissector)
        return self

    def with_input(self, input_value: str) -> "DissectorTester":
        self.inputs.append(input_value)
        return self

    def with_path_prefix(self, prefix: str) -> "DissectorTester":
        self.path_prefix = prefix
        return self

    def verbose(self) -> "DissectorTester":
        self._verbose = True
        return self

    # expectations ------------------------------------------------------

    def expect(self, fieldname: str, value: Union[str, int, float]) -> "DissectorTester":
        if isinstance(value, bool):
            raise TypeError("bool expectation is invalid")
        if isinstance(value, str):
            return self.expect_string(fieldname, value)
        if isinstance(value, int):
            return self.expect_long(fieldname, value)
        return self.expect_double(fieldname, value)

    def expect_string(self, fieldname: str, value: Optional[str]) -> "DissectorTester":
        self.expectations.append(("string", fieldname, value))
        return self

    def expect_long(self, fieldname: str, value: Optional[int]) -> "DissectorTester":
        self.expectations.append(("long", fieldname, value))
        return self

    def expect_double(self, fieldname: str, value: Optional[float]) -> "DissectorTester":
        self.expectations.append(("double", fieldname, value))
        return self

    def expect_null(self, fieldname: str) -> "DissectorTester":
        self.expectations.append(("string", fieldname, None))
        return self

    def expect_absent_string(self, fieldname: str) -> "DissectorTester":
        self.expectations.append(("absent_string", fieldname, None))
        return self

    def expect_absent_long(self, fieldname: str) -> "DissectorTester":
        self.expectations.append(("absent_long", fieldname, None))
        return self

    def expect_absent_double(self, fieldname: str) -> "DissectorTester":
        self.expectations.append(("absent_double", fieldname, None))
        return self

    def expect_possible(self, fieldname: str) -> "DissectorTester":
        self.possible_expectations.append(fieldname)
        return self

    def expect_absent_possible(self, fieldname: str) -> "DissectorTester":
        self.absent_possible.append(fieldname)
        return self

    # execution ---------------------------------------------------------

    def _build_parser(self) -> Parser:
        if not self.dissectors:
            raise AssertionError("No dissectors were specified")
        parser = Parser(TestRecord)
        root_type = self.dissectors[0].get_input_type()
        if self.path_prefix is not None:
            wrapper = _PrefixRootDissector(
                "ROOTINPUT", self.path_prefix, root_type
            )
            parser.add_dissector(wrapper)
            parser.set_root_type("ROOTINPUT")
        else:
            parser.set_root_type(root_type)
        for d in self.dissectors:
            parser.add_dissector(d)

        kinds_for_field: Dict[str, set] = {}
        for kind, fieldname, _ in self.expectations:
            kinds_for_field.setdefault(fieldname, set()).add(kind.replace("absent_", ""))
        for fieldname, kinds in kinds_for_field.items():
            if "string" in kinds:
                parser.add_parse_target("set_string_value", fieldname)
            if "long" in kinds:
                parser.add_parse_target("set_long_value", fieldname)
            if "double" in kinds:
                parser.add_parse_target("set_double_value", fieldname)
        return parser

    def check_expectations(self) -> "DissectorTester":
        if not self.expectations and not self.possible_expectations and not self.absent_possible:
            raise AssertionError("No expectations were specified")

        parser = self._build_parser()

        if self.possible_expectations or self.absent_possible:
            paths = parser.get_possible_paths()
            for fieldname in self.possible_expectations:
                assert fieldname in paths, (
                    f"Expected possible path {fieldname!r}; got:\n  " + "\n  ".join(paths)
                )
            for fieldname in self.absent_possible:
                assert fieldname not in paths, (
                    f"Path {fieldname!r} should NOT be possible"
                )

        if not self.expectations:
            return self
        if not self.inputs:
            raise AssertionError("No inputs were specified")

        # Serialization round-trip: every test also proves picklability
        # (reference clones via Java serialization, DissectorTester.java:257-264).
        parser.assemble_dissectors()
        parser = pickle.loads(pickle.dumps(parser))

        from .core.fields import cleanup_field_value

        for input_value in self.inputs:
            record: TestRecord = parser.parse(input_value)
            failures: List[str] = []
            for kind, fieldname, expected in self.expectations:
                key = cleanup_field_value(fieldname)
                if kind == "string":
                    actual = record.string_values.get(key, "<<<ABSENT>>>")
                elif kind == "long":
                    actual = record.long_values.get(key, "<<<ABSENT>>>")
                elif kind == "double":
                    actual = record.double_values.get(key, "<<<ABSENT>>>")
                elif kind == "absent_string":
                    if key in record.string_values:
                        failures.append(
                            f"{fieldname}: expected ABSENT string, got "
                            f"{record.string_values[key]!r}"
                        )
                    continue
                elif kind == "absent_long":
                    if key in record.long_values:
                        failures.append(
                            f"{fieldname}: expected ABSENT long, got "
                            f"{record.long_values[key]!r}"
                        )
                    continue
                elif kind == "absent_double":
                    if key in record.double_values:
                        failures.append(
                            f"{fieldname}: expected ABSENT double, got "
                            f"{record.double_values[key]!r}"
                        )
                    continue
                else:  # pragma: no cover
                    raise AssertionError(kind)
                if actual != expected:
                    failures.append(
                        f"{fieldname} ({kind}): expected {expected!r}, got {actual!r}"
                    )
            if failures:
                raise AssertionError(
                    f"Input {input_value!r} failed expectations:\n  "
                    + "\n  ".join(failures)
                )
        return self
