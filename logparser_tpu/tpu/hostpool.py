"""Shared host-side worker pool for Arrow column assembly.

One parallelism knob for the whole delivery path: ``TpuBatchParser``
owns an :class:`AssemblyPool` whose worker count both (a) fans the
per-column Arrow assembly (`arrow_bridge.batch_to_arrow`) across Python
threads and (b) feeds the native memcpy fan-outs (`gather_spans`,
`build_views`, `views_interleave`) their thread budget, so the two
layers never oversubscribe each other: pooled per-column tasks run their
native calls single-threaded, unpooled batched calls get the full
budget.

Threads, not processes: every heavy step (native memcpy fan-out via
ctypes, numpy reductions, pyarrow buffer construction) releases the GIL,
and the assembled Arrow buffers must reference the batch's host memory
zero-copy — a process pool would force a serialize/copy per column.
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence


# Below this many rows the per-column fan-out costs more in task
# dispatch + GIL churn than it overlaps (measured on a 2-core host,
# copy mode: 0.5x at 8k rows, 1.39x at 32k, 2.27x at 64k): smaller
# batches take the serial/batched path.
MIN_POOLED_ROWS = 32768

# View-mode column assembly is mostly small numpy/pyarrow work that
# HOLDS the GIL (the byte-heavy stages are already threaded inside the
# native calls), so fanning it out needs enough workers to hide the
# Python overhead: 2-worker pooling measured 0.86x at 64k rows.  Copy
# mode has no such floor — its per-column work is one big GIL-released
# native gather.
VIEW_POOL_MIN_WORKERS = 4


def default_workers() -> int:
    """The delivery path's default parallelism (the native module's
    memcpy fan-out default: min(8, cpu_count))."""
    from ..native import _default_threads

    return _default_threads()


class AssemblyPool:
    """Lazily-started shared thread pool with a fixed worker count.

    ``workers == 1`` never starts threads — every ``run_all`` executes
    serially in the caller, so a 1-worker pool is bit-for-bit the
    pre-pool code path (the thread-count parity suite depends on it).
    """

    def __init__(self, workers: Optional[int] = None,
                 native_threads: Optional[int] = None):
        self.workers = max(1, int(workers if workers else default_workers()))
        # Optional decoupled budget for BATCHED native calls (one call
        # covering every column).  bench.py's pool=1 baseline uses this
        # to reproduce the pre-pool serial path exactly: column fan-out
        # off, native memcpy fan-out at the module default.
        self._native_threads = native_threads
        self._executor: Optional[ThreadPoolExecutor] = None
        self._closed = False
        self._lock = threading.Lock()

    @property
    def native_threads(self) -> int:
        """Thread budget for a BATCHED native call issued outside the
        pool (one call covering every column): the full worker count
        unless explicitly overridden."""
        if self._native_threads is not None:
            return self._native_threads
        return self.workers

    def _get_executor(self) -> Optional[ThreadPoolExecutor]:
        if self._executor is None:
            with self._lock:
                if self._closed:
                    return None  # terminal: never respawn after close()
                if self._executor is None:
                    self._executor = ThreadPoolExecutor(
                        max_workers=self.workers,
                        thread_name_prefix="lp-assembly",
                    )
        return self._executor

    def run_all(self, tasks: Sequence[Callable[[], Any]]) -> List[Any]:
        """Run independent thunks, returning results in order.  Serial
        when the pool is 1-wide, closed, or there is nothing to
        overlap; the first raised exception propagates either way.

        The pooled path feeds the metrics registry (batch granularity —
        once per run plus one cheap gauge/counter update per TASK, and
        tasks are per-column, not per-line): queue depth and in-flight
        gauges, busy/wall second counters (utilization =
        busy / (wall * workers)), and a per-task (per-column assembly)
        time histogram.  The 1-wide serial path stays untouched — it is
        the bit-for-bit pre-pool baseline the parity suite pins."""
        if self.workers == 1 or len(tasks) <= 1:
            return [t() for t in tasks]
        ex = self._get_executor()
        if ex is None:
            return [t() for t in tasks]

        import time

        from ..observability import metrics

        reg = metrics()
        reg.gauge_set("hostpool_workers", self.workers)
        reg.increment("hostpool_runs_total")
        reg.increment("hostpool_tasks_total", len(tasks))
        reg.gauge_add("hostpool_queue_depth", len(tasks))

        def timed(t: Callable[[], Any]) -> Any:
            # Submitted -> running: the task leaves the queue.
            reg.gauge_add("hostpool_queue_depth", -1)
            reg.gauge_add("hostpool_active_workers", 1)
            t0 = time.perf_counter()
            try:
                return t()
            finally:
                dt = time.perf_counter() - t0
                reg.gauge_add("hostpool_active_workers", -1)
                reg.increment("hostpool_busy_seconds_total", dt)
                reg.observe("hostpool_task_seconds", dt)

        t_run = time.perf_counter()
        try:
            return list(ex.map(timed, tasks))
        finally:
            reg.increment(
                "hostpool_wall_seconds_total", time.perf_counter() - t_run
            )

    def submit(self, fn: Callable[[], Any]):
        """Submit ONE thunk for background execution; returns a Future,
        or None when the pool is serial (1-wide) or closed — callers
        then run the thunk inline.  Used by the batch runtime's rescue
        path to overlap the host oracle parse with the CSR/column
        materialization; run_all's per-task metrics stay per-column, so
        this path only counts the run."""
        if self.workers == 1:
            return None
        ex = self._get_executor()
        if ex is None:
            return None

        from ..observability import metrics

        metrics().increment("hostpool_runs_total")
        return ex.submit(fn)

    def close(self) -> None:
        """Terminal: later run_all calls execute serially instead of
        respawning threads (a retained BatchResult may outlive its
        parser and still deliver to_arrow correctly)."""
        with self._lock:
            self._closed = True
            if self._executor is not None:
                self._executor.shutdown(wait=False)
                self._executor = None

    # Pools never pickle (parser artifacts rebuild them on load).
    def __getstate__(self):  # pragma: no cover - defensive
        return {"workers": self.workers}

    def __setstate__(self, state):  # pragma: no cover - defensive
        self.__init__(state.get("workers"))
