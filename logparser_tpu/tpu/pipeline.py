"""Fused device pipeline: split + typed post-stages -> ONE packed [K, B] int32.

Plain-XLA execution everywhere (TPU and the CPU test meshes).  This is the
rebuild's answer to the reference's per-line `Matcher.find()` hot loop
(TokenFormatDissector.java:243-275): a compiled split program executed as a
vector automaton, not a backtracking regex.  The workload — elementwise
compares + masked reductions — is exactly the shape XLA's fusion engine
schedules near-optimally on the VPU; a hand-written Pallas kernel of the
same pipeline measured ~4.5x SLOWER on v5e (one HBM pass either way, and
the kernel's lane rolls cost more than XLA's fused selects) and Mosaic
cannot lower the chained stages at all, so the kernel was removed (see
COMPONENTS.md, "Pallas kernel" ADR; round-2 measurements in git history).

The output is a single packed ``[K, B]`` int32 array (one row per output
component, described by :class:`PackedLayout`) so the host needs exactly ONE
device->host fetch per batch — transfer round-trips, not bandwidth, dominate
on tunneled/virtualized TPU attachments.

Shift discipline: every data movement is a left-shift of the line axis with
a zero-filled tail (``shift_zero``); callers mask every position past the
span/line end.
"""
from __future__ import annotations

from dataclasses import dataclass, field as dataclass_field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import postproc, timeparse
from .program import CS_ANY, DeviceProgram


@dataclass(frozen=True)
class FieldPlan:
    """How one requested field is produced on device ('host' = oracle-only).

    A plan is a token capture plus a chain of span-transform ``steps``
    (the device analogues of sub-dissectors: first-line split, URI split,
    ...) ending in a terminal decode ``kind``:

    - ``span``      the final sub-span itself (string field)
    - ``long``      digit span -> int64 (null_mode handles the CLF '-' and
                    zero<->null converter semantics)
    - ``secmillis`` "sec.millis" decimal span -> epoch millis
    - ``ts``        fixed-layout timestamp -> component bundle; ``comp``
                    names the requested output (epoch/year/.../monthname)
                    and ``meta`` carries the DeviceTimeLayout
    - ``host``      oracle-only
    """

    field_id: str                 # cleaned "TYPE:path"
    kind: str                     # span | long | secmillis | ts | host
    token_index: int = -1
    steps: Tuple[Tuple[str, str], ...] = ()   # e.g. (("fl", "uri"),)
    comp: str = ""                # ts output name / CSR wildcard key
    meta: object = None           # ts: DeviceTimeLayout; qscsr: mode
    null_mode: str = ""           # "" | dash_null | dash_zero | zero_null
    scale: int = 1                # value multiplier (ms -> us converters)
    # qscsr set-cookie only: the per-cookie attribute requested THROUGH the
    # wildcard (value/expires/path/domain/comment); comp is the cookie name.
    # Materialized host-side per matched row (cookies.parse_attrs).
    attr: str = ""


# ---------------------------------------------------------------------------
# Shifts: the only data-movement primitive in the pipeline.
# ---------------------------------------------------------------------------


from .postproc import shift_zero  # the shared zero-fill shift primitive


# ---------------------------------------------------------------------------
# Split program (shared by runtime.run_program and the packed pipeline).
# ---------------------------------------------------------------------------


def _table_intervals(table: np.ndarray) -> List[Tuple[int, int]]:
    """Decompose a 256-entry bool charset table into [lo, hi] byte intervals,
    so membership compiles to a few vector compares instead of a gather."""
    intervals: List[Tuple[int, int]] = []
    lo = None
    for b in range(257):
        inside = b < 256 and bool(table[b])
        if inside and lo is None:
            lo = b
        elif not inside and lo is not None:
            intervals.append((lo, b - 1))
            lo = None
    return intervals


def _charset_mask(b32: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
    """[B, L] bool: byte admitted by the charset, via interval compares."""
    intervals = _table_intervals(table)
    if not intervals:
        return jnp.zeros(b32.shape, dtype=bool)
    if len(intervals) == 1 and intervals[0] == (0, 255):
        return jnp.ones(b32.shape, dtype=bool)
    ok = None
    for lo, hi in intervals:
        part = (b32 == lo) if lo == hi else ((b32 >= lo) & (b32 <= hi))
        ok = part if ok is None else (ok | part)
    return ok


# ---------------------------------------------------------------------------
# Escaped-quote decoding (round 18, ROADMAP direction 5).  Apache's
# ap_escape_logitem writes `\"` for a quote inside a quoted field (%r /
# %{User-Agent}i ...) and `\\` for a backslash, so in a well-formed log a
# DATA quote always sits behind an odd-length backslash run and a field
# TERMINATOR behind an even one.  The reference regex is escape-UNAWARE
# (FORMAT_STRING is a bare lazy `.*?`): it accepts these lines through
# backtracking and delivers the span VERBATIM, backslashes included
# (httpd/utils_apache.py replicates the upstream bug that keeps the
# decode dormant).  The device split therefore models the terminator
# choice, not a byte rewrite: a quote-led separator occurrence whose
# quote has odd backslash parity is masked out of the cursor search.
#
# Soundness (device-valid must imply byte-identity with the host):
# - FINAL op (the format's last separator, host rest is `$`): masking is
#   unconditionally exact.  The host's lazy scan tries occurrences in
#   order and only an occurrence ENDING the line can satisfy the end
#   anchor; every masked (odd-parity) occurrence the device skipped lies
#   strictly before its chosen terminator, hence before line end, hence
#   the host rejects it too and lands on the same terminator.
# - NON-final op: the host might match at a skipped occurrence (its rest
#   is a full regex tail, satisfiable by hostile bytes), and proving it
#   cannot requires evaluating that tail.  Such lines are NOT claimed:
#   any skipped occurrence before the chosen terminator invalidates the
#   line and routes it to the oracle, which applies the reference's
#   backtracking exactly.  (Realistic escaped quotes inside %r/referer
#   rarely form a separator occurrence at all — `\"x` contains no
#   `" `/`" "` — so the conservative arm costs only genuinely ambiguous
#   lines, which also failed the device split before this round.)
#
# Plausibility is untouched: the host regex is escape-unaware, so the
# UNMASKED occurrence masks remain the sound model (regex-accept still
# implies plausible).
# ---------------------------------------------------------------------------

_BACKSLASH = 0x5C


def esc_quote_op_flags(program: DeviceProgram) -> Dict[int, bool]:
    """{op position: op is the program's final op} for every until_lit
    whose separator begins with a quote over an unconstrained (CS_ANY)
    capture — the quoted-field shape escape-parity masking applies to."""
    ops = program.ops
    return {
        i: i == len(ops) - 1
        for i, op in enumerate(ops)
        if op.kind == "until_lit"
        and op.lit[:1] == b'"'
        and op.charset == CS_ANY
    }


def escaped_lead_positions(b32: jnp.ndarray) -> jnp.ndarray:
    """[B, L] bool: the maximal backslash run immediately before position
    p has ODD length — a quote AT p is escaped data under Apache's
    ap_escape_logitem convention, not a field terminator.  One vectorized
    O(B*L) pass (compare + running max), independent of the byte at p;
    zero-padding past line end breaks runs, so no lengths mask is
    needed."""
    B, L = b32.shape
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, L), 1)
    non_bs = b32 != _BACKSLASH
    last_non_bs = jax.lax.cummax(
        jnp.where(non_bs, pos, -1), axis=1
    )
    prev_last = jnp.concatenate(
        [jnp.full((B, 1), -1, dtype=jnp.int32), last_non_bs[:, :-1]],
        axis=1,
    )
    run_before = (pos - 1) - prev_last
    return (run_before & 1) == 1


def compute_split_dense(
    program: DeviceProgram,
    b32: jnp.ndarray,
    lengths: jnp.ndarray,
    need_plausible: bool = False,
) -> Tuple[List[jnp.ndarray], List[jnp.ndarray], jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """Run the split program over int32 byte rows.

    Returns (start_list, end_list, valid, plausible, esc_hit): per-token
    [B] cursors plus the per-line validity mask.  Gather-free: precomputed
    literal-match masks and charset masks + masked reductions.

    ``esc_hit`` (None for programs without a quoted-field op) marks lines
    whose quoted-field cursor search skipped a backslash-escaped separator
    occurrence under the escape-parity mask (see the module comment above
    this function) — on a line that stays valid, the device decoded an
    escaped quote the pre-round-18 split would have rejected.

    ``plausible`` (only when need_plausible) is a SOUND over-approximation of
    "the format's real regex could accept this line": all literal separators
    occur in order (greedy first-occurrence matching is exact for subsequence
    existence, so regex-accept implies plausible; valid implies plausible).
    Multi-format winner selection uses it to avoid claiming a line for format
    k when an earlier format j < k — whose non-backtracking device automaton
    false-rejected the line — might still accept it: such lines go to the
    host oracle, which applies the reference's registration-priority
    semantics exactly."""
    B, L = b32.shape
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, L), 1)
    cursor = jnp.zeros(B, dtype=jnp.int32)
    valid = jnp.ones(B, dtype=bool)
    n_tok = len(program.tokens)
    zeros = jnp.zeros(B, dtype=jnp.int32)
    starts: List[jnp.ndarray] = [zeros] * n_tok
    ends: List[jnp.ndarray] = [zeros] * n_tok

    # Literal-match masks for every distinct separator, computed once: full
    # literal matches starting at this position AND fits inside the line.
    lit_masks: Dict[bytes, jnp.ndarray] = {}
    for lit in sorted({op.lit for op in program.ops if op.lit}):
        m = None
        for k, byte in enumerate(lit):
            part = shift_zero(b32, k) == byte if k else (b32 == byte)
            m = part if m is None else (m & part)
        lit_masks[lit] = m & (pos + len(lit) <= lengths[:, None])

    cs_masks = {
        name: _charset_mask(b32, program.charset_table[cid])
        for name, cid in program.charset_ids.items()
    }

    esc_ops = esc_quote_op_flags(program)
    esc_mask = escaped_lead_positions(b32) if esc_ops else None
    esc_hit = jnp.zeros(B, dtype=bool) if esc_ops else None

    def check_charset(start, end, op, valid):
        cs_ok = cs_masks[op.charset]
        outside = (pos < start[:, None]) | (pos >= end[:, None])
        span_ok = jnp.all(cs_ok | outside, axis=1)
        width = end - start
        # CLF alternations ('number|-'): a lone '-' is legal even though the
        # charset also admits digits; min_len floor of 1 covers both arms.
        ok = valid & span_ok & (width >= op.min_len)
        if op.max_len:
            # Fixed/bounded-width regexes (e.g. '.' for $pipe matches ONE
            # byte): without this the device accepts longer spans the real
            # regex rejects — silently diverging instead of falling back.
            ok = ok & (width <= op.max_len)
        return ok

    # Plausibility: chase each separator's FIRST occurrence at/after a free
    # cursor — subsequence existence, for which greedy first-occurrence
    # matching is exact — with three additional SOUND anchorings (each is a
    # consequence of regex acceptance, so regex-accept still implies
    # plausible): (a) a leading literal must match at position 0 (the regex
    # is ^-anchored); (b) the final literal must end exactly at the line end
    # ($-anchored); (c) when the last token is to_end with a bounded
    # charset, the preceding separator must sit past the last
    # charset-violating byte.  (b)/(c) keep e.g. `common` from looking
    # plausible on every `combined` line (spaces occur everywhere), which
    # would otherwise send all those lines to the oracle.
    plausible = None
    if need_plausible:
        ops_list = list(program.ops)
        plausible = jnp.ones(B, dtype=bool)
        p_cursor = jnp.zeros(B, dtype=jnp.int32)
        for idx, op in enumerate(ops_list):
            if not op.lit:
                continue  # to_end: handled via the preceding separator
            k = len(op.lit)
            is_first = idx == 0 and op.kind == "lit"
            remaining = ops_list[idx + 1 :]
            is_final_sep = not any(o.lit for o in remaining)
            usable = lit_masks[op.lit]
            if is_first:
                usable = usable & (pos == 0)
            else:
                usable = usable & (pos >= p_cursor[:, None])
            if is_final_sep and not remaining:
                # Trailing separator: the regex is end-anchored.
                usable = usable & (pos == lengths[:, None] - k)
            elif is_final_sep and remaining[0].kind == "to_end":
                tail = remaining[0]
                if tail.charset != CS_ANY and not tail.narrow:
                    # A NARROW charset under-approximates the regex's set,
                    # so it must not anchor plausibility (regex-accept
                    # must still imply plausible).
                    # The to_end token spans [q + k, length); it can only
                    # satisfy its charset if q + k is past the last
                    # violating byte.
                    bad = ~cs_masks[tail.charset] & (pos < lengths[:, None])
                    last_bad = jnp.max(
                        jnp.where(bad, pos, -1), axis=1
                    ).astype(jnp.int32)
                    usable = usable & (pos >= (last_bad - k + 1)[:, None])
                # until_lit final sep followed by to_end cannot happen (the
                # separator belongs to until_lit and to_end has none), so q
                # need not sit at line end here.
            found = jnp.min(jnp.where(usable, pos, L), axis=1).astype(jnp.int32)
            plausible = plausible & (found < L)
            p_cursor = found + k

    for oi, op in enumerate(program.ops):
        if op.kind == "lit":
            # Literal matches exactly at the cursor: probe the match mask
            # with a one-hot reduction (no gather).
            ok = jnp.any(lit_masks[op.lit] & (pos == cursor[:, None]), axis=1)
            valid = valid & ok
            cursor = cursor + len(op.lit)
        elif op.kind == "until_lit":
            usable = lit_masks[op.lit] & (pos >= cursor[:, None])
            if oi in esc_ops:
                # Escape-parity mask: an occurrence whose quote sits
                # behind an odd backslash run is data, not a terminator.
                skipped = usable & esc_mask
                usable = usable & ~esc_mask
                first_skip = jnp.min(
                    jnp.where(skipped, pos, L), axis=1
                ).astype(jnp.int32)
            found = jnp.min(jnp.where(usable, pos, L), axis=1).astype(jnp.int32)
            if oi in esc_ops:
                had_skip = first_skip < found
                if esc_ops[oi]:
                    # Final op: skipping is exact (host rest is `$`).
                    esc_hit = esc_hit | had_skip
                else:
                    # Non-final op: the host might match at the skipped
                    # occurrence — don't claim, let the oracle decide.
                    valid = valid & ~had_skip
            token_valid = found < L
            start = cursor
            end = jnp.where(token_valid, found, cursor)
            valid = check_charset(start, end, op, valid & token_valid)
            starts[op.token_index] = start
            ends[op.token_index] = end
            cursor = end + len(op.lit)
        elif op.kind == "to_end":
            start = cursor
            end = lengths
            valid = check_charset(start, end, op, valid)
            starts[op.token_index] = start
            ends[op.token_index] = end
            cursor = end
        else:  # pragma: no cover
            raise AssertionError(op.kind)

    # The whole line must be consumed (the regex is end-anchored).
    valid = valid & (cursor == lengths)
    return starts, ends, valid, plausible, esc_hit


# ---------------------------------------------------------------------------
# Bitplane split executor.  The dense splitter above costs one full [B, L]
# reduction pass PER op (each until_lit first-occurrence search and each
# charset span check reads the whole buffer again); the sequential cursor
# dependency keeps XLA from fusing the passes, so ~14 passes dominated the
# round-3 kernel profile (ROADMAP item 1).  The bitplane form packs the
# buffer ONCE into per-byte-class position bitplanes — [B, C] uint32 words,
# C = ceil(L/32), bit j of word c = "class matches at position c*32+j" —
# and then every search, literal probe, charset span check and plausibility
# anchoring runs on the planes with word arithmetic (shift/AND/popcount +
# tiny reductions over C).  One O(B*L) pass total instead of ~14.
#
# Exactness: multi-byte literal occurrence masks are derived from the
# single-byte planes with cross-word shifts, and every resolution below
# reproduces compute_split_dense bit-for-bit (locked by
# tests/test_bitplane_split.py differential sweeps).
# ---------------------------------------------------------------------------

_PLANE_W = 32
_PLANE_FULL = np.uint32(0xFFFFFFFF)


def _plane_pack(pred: jnp.ndarray, C: int) -> jnp.ndarray:
    """[B, C*32] bool -> [B, C] uint32 position bitplane."""
    B = pred.shape[0]
    w = pred.reshape(B, C, _PLANE_W)
    weights = jnp.uint32(1) << jnp.arange(_PLANE_W, dtype=jnp.uint32)
    return jnp.sum(
        jnp.where(w, weights, jnp.uint32(0)), axis=2, dtype=jnp.uint32
    )


def _plane_shr(plane: jnp.ndarray, k: int) -> jnp.ndarray:
    """Bit p of the result = bit p+k of the input (cross-word carry).

    Arbitrary k: whole words shift as column moves, the remainder as a
    bit shift (k is the literal byte offset, so separators longer than
    one 32-bit word still derive correctly)."""
    wshift, bshift = divmod(k, _PLANE_W)
    if wshift:
        plane = jnp.pad(plane[:, wshift:], ((0, 0), (0, wshift)))
    if bshift:
        nxt = jnp.pad(plane[:, 1:], ((0, 0), (0, 1)))
        plane = (plane >> jnp.uint32(bshift)) | (
            nxt << jnp.uint32(_PLANE_W - bshift)
        )
    return plane


def _plane_cutoff(thresh: jnp.ndarray, C: int) -> jnp.ndarray:
    """[B] threshold -> [B, C] plane with bits set at positions < thresh."""
    word_idx = jnp.arange(C, dtype=jnp.int32)[None, :]
    rel = jnp.clip(thresh[:, None] - word_idx * _PLANE_W, 0, _PLANE_W)
    partial = (jnp.uint32(1) << rel.astype(jnp.uint32)) - jnp.uint32(1)
    return jnp.where(rel >= _PLANE_W, _PLANE_FULL, partial)


def _plane_word_at(plane: jnp.ndarray, wi: jnp.ndarray, C: int) -> jnp.ndarray:
    """Select word wi per row (one-hot sum; out-of-range -> 0)."""
    idx = jnp.arange(C, dtype=jnp.int32)[None, :]
    return jnp.sum(
        jnp.where(idx == wi[:, None], plane, jnp.uint32(0)),
        axis=1, dtype=jnp.uint32,
    )


def _plane_first_ge(
    plane: jnp.ndarray, cursor: jnp.ndarray, C: int, L: int
) -> jnp.ndarray:
    """First set-bit position >= cursor per row; L when none."""
    cw = cursor // _PLANE_W
    cb = (cursor % _PLANE_W).astype(jnp.uint32)
    idx = jnp.arange(C, dtype=jnp.int32)[None, :]
    tail = _PLANE_FULL << cb[:, None]
    keep = jnp.where(
        idx == cw[:, None], plane & tail,
        jnp.where(idx > cw[:, None], plane, jnp.uint32(0)),
    )
    nz = keep != 0
    first_w = jnp.min(jnp.where(nz, idx, C), axis=1)
    word = _plane_word_at(keep, first_w, C)
    low = word & (jnp.uint32(0) - word)
    bit = jax.lax.population_count(low - jnp.uint32(1))
    found = first_w * _PLANE_W + bit.astype(jnp.int32)
    return jnp.where(word != 0, found, L)


def _plane_test_bit(plane: jnp.ndarray, p: jnp.ndarray, C: int) -> jnp.ndarray:
    """Bit test at position p per row (out-of-range -> False)."""
    word = _plane_word_at(plane, p // _PLANE_W, C)
    bit = (word >> (p % _PLANE_W).astype(jnp.uint32)) & jnp.uint32(1)
    return (bit != 0) & (p >= 0) & (p < C * _PLANE_W)


def _plane_any_in_range(
    plane: jnp.ndarray, start: jnp.ndarray, end: jnp.ndarray, C: int
) -> jnp.ndarray:
    """Any set bit at a position in [start, end) per row."""
    rng = _plane_cutoff(end, C) & ~_plane_cutoff(start, C)
    return jnp.any((plane & rng) != 0, axis=1)


def _plane_last_set(plane: jnp.ndarray, C: int) -> jnp.ndarray:
    """Highest set-bit position per row; -1 when the plane is empty."""
    idx = jnp.arange(C, dtype=jnp.int32)[None, :]
    nz = plane != 0
    last_w = jnp.max(jnp.where(nz, idx, -1), axis=1)
    word = _plane_word_at(plane, last_w, C)
    w = word
    for s in (1, 2, 4, 8, 16):
        w = w | (w >> jnp.uint32(s))
    high = jax.lax.population_count(w).astype(jnp.int32) - 1
    return jnp.where(last_w >= 0, last_w * _PLANE_W + high, -1)


def compute_split(
    program: DeviceProgram,
    b32: jnp.ndarray,
    lengths: jnp.ndarray,
    need_plausible: bool = False,
) -> Tuple[List[jnp.ndarray], List[jnp.ndarray], jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """Bitplane execution of the split program — semantically identical to
    :func:`compute_split_dense` (same return contract; see its docstring for
    the plausibility soundness argument and the escape-parity module
    comment for ``esc_hit``), one O(B*L) packing pass total."""
    if any(0 in op.lit for op in program.ops if op.lit):
        # A NUL byte inside a separator literal would collide with the
        # zero padding the plane derivation relies on.
        return compute_split_dense(program, b32, lengths, need_plausible)
    B, L = b32.shape
    C = -(-L // _PLANE_W)
    Lp = C * _PLANE_W
    bp = jnp.pad(b32, ((0, 0), (0, Lp - L))) if Lp != L else b32

    lit_bytes = sorted({bt for op in program.ops if op.lit for bt in op.lit})
    charsets = sorted({
        op.charset for op in program.ops
        if op.kind != "lit" and op.charset != CS_ANY
    })
    byte_planes = {bt: _plane_pack(bp == bt, C) for bt in lit_bytes}
    viol_planes = {
        cs: _plane_pack(
            ~_charset_mask(bp, program.charset_table[program.charset_ids[cs]]),
            C,
        )
        for cs in charsets
    }
    lit_planes: Dict[bytes, jnp.ndarray] = {}
    for lit in sorted({op.lit for op in program.ops if op.lit}):
        m = byte_planes[lit[0]]
        for k, bt in enumerate(lit[1:], 1):
            m = m & _plane_shr(byte_planes[bt], k)
        # Same guard as the dense lit_masks: the occurrence must fit
        # inside the line (pos + len(lit) <= lengths).
        lit_planes[lit] = m & _plane_cutoff(lengths - (len(lit) - 1), C)

    esc_ops = esc_quote_op_flags(program)
    esc_plane = (
        _plane_pack(escaped_lead_positions(bp), C) if esc_ops else None
    )

    zeros = jnp.zeros(B, dtype=jnp.int32)
    cursor = zeros
    valid = jnp.ones(B, dtype=bool)
    esc_hit = jnp.zeros(B, dtype=bool) if esc_ops else None
    n_tok = len(program.tokens)
    starts: List[jnp.ndarray] = [zeros] * n_tok
    ends: List[jnp.ndarray] = [zeros] * n_tok

    def check_charset(start, end, op, valid):
        if op.charset != CS_ANY:
            bad = _plane_any_in_range(viol_planes[op.charset], start, end, C)
            valid = valid & ~bad
        width = end - start
        ok = valid & (width >= op.min_len)
        if op.max_len:
            ok = ok & (width <= op.max_len)
        return ok

    for oi, op in enumerate(program.ops):
        if op.kind == "lit":
            ok = _plane_test_bit(lit_planes[op.lit], cursor, C)
            valid = valid & ok
            cursor = cursor + len(op.lit)
        elif op.kind == "until_lit":
            if oi in esc_ops:
                # Escape-parity mask (see the dense variant): search the
                # even-parity plane; a skipped odd-parity occurrence is
                # exact for the final op, un-claims the line otherwise.
                found = _plane_first_ge(
                    lit_planes[op.lit] & ~esc_plane, cursor, C, L
                )
                first_skip = _plane_first_ge(
                    lit_planes[op.lit] & esc_plane, cursor, C, L
                )
                had_skip = first_skip < found
                if esc_ops[oi]:
                    esc_hit = esc_hit | had_skip
                else:
                    valid = valid & ~had_skip
            else:
                found = _plane_first_ge(lit_planes[op.lit], cursor, C, L)
            token_valid = found < L
            start = cursor
            end = jnp.where(token_valid, found, cursor)
            valid = check_charset(start, end, op, valid & token_valid)
            starts[op.token_index] = start
            ends[op.token_index] = end
            cursor = end + len(op.lit)
        elif op.kind == "to_end":
            start = cursor
            end = lengths
            valid = check_charset(start, end, op, valid)
            starts[op.token_index] = start
            ends[op.token_index] = end
            cursor = end
        else:  # pragma: no cover
            raise AssertionError(op.kind)
    valid = valid & (cursor == lengths)

    plausible = None
    if need_plausible:
        # Same chase as compute_split_dense (see its inline comments for
        # the soundness of each anchoring), resolved on the planes.
        ops_list = list(program.ops)
        plausible = jnp.ones(B, dtype=bool)
        p_cursor = zeros
        for idx, op in enumerate(ops_list):
            if not op.lit:
                continue
            k = len(op.lit)
            is_first = idx == 0 and op.kind == "lit"
            remaining = ops_list[idx + 1:]
            is_final_sep = not any(o.lit for o in remaining)
            plane = lit_planes[op.lit]
            lower = p_cursor
            exact: Optional[jnp.ndarray] = None
            if is_first:
                exact = zeros
            if is_final_sep and not remaining:
                e2 = lengths - k
                exact = e2 if exact is None else jnp.where(
                    exact == e2, exact, jnp.full(B, -1, jnp.int32)
                )
            elif is_final_sep and remaining[0].kind == "to_end":
                tail = remaining[0]
                if tail.charset != CS_ANY and not tail.narrow:
                    masked = (
                        viol_planes[tail.charset]
                        & _plane_cutoff(lengths, C)
                    )
                    last_bad = _plane_last_set(masked, C)
                    lower = jnp.maximum(lower, last_bad - k + 1)
            if exact is not None:
                hit = _plane_test_bit(plane, exact, C) & (exact >= lower)
                found = jnp.where(hit, exact, L)
            else:
                found = _plane_first_ge(plane, lower, C, L)
            plausible = plausible & (found < L)
            p_cursor = found + k
    return starts, ends, valid, plausible, esc_hit


# ---------------------------------------------------------------------------
# Packed output layout: every output component is a bit slot (row, shift,
# bits) in the [K, B] int32 result.  Span-producing kinds pack
# start|len|ok into ONE row (13+13+1 bits; L is capped at 8191 =
# runtime.DEFAULT_MAX_LINE_LEN); numeric/epoch aux bits (ok/null/lo_digits)
# share trailing "meta" rows.  Device->host transfer is round-trip- and
# bandwidth-bound on tunneled attachments, so rows are precious.
# ---------------------------------------------------------------------------

_SPAN_BITS = 13          # start / len each; supports L up to 8191

Slot = Tuple[int, int, int]   # (row, shift, bits); bits=0 -> full int32 row


def ts_group_key(plan: FieldPlan) -> str:
    """All ts plans over the same token+steps share one component bundle."""
    return f"@ts:{plan.token_index}:{plan.steps!r}"


# Default segment slots per CSR wildcard split (query params / cookies).
# Lines with more segments than slots are routed to the oracle AND flagged
# in the validity row (CSR_OVERFLOW_BIT); TpuBatchParser reacts by doubling
# the layout's slot count (up to CSR_SLOTS_MAX) and re-running the batch, so
# query-heavy corpora pay a bounded number of recompiles instead of a
# per-line oracle cliff.
CSR_SLOTS = 16
CSR_SLOTS_MAX = 128

# CSR scan-window budget, in span bytes per segment slot.  split_csr runs
# its scans over a compact [B, slots * CSR_WINDOW_PER_SLOT] gather of the
# span instead of the full padded line — spans (query strings, cookie
# headers) are tiny next to L, and the scans are the kernel's dominant
# cost.  A span longer than the window raises the same CSR_OVERFLOW_BIT
# as running out of slots, and the same adaptive response (double the
# slots, window scales along) resolves it; at CSR_SLOTS_MAX the window
# covers 1024 bytes and longer spans stay oracle-bound, exactly like
# slot exhaustion.
CSR_WINDOW_PER_SLOT = 8

# Scan-window budget for the URI fast split (path + query + authority in
# one span, so roomier than a lone query string): 12 bytes/slot puts the
# default window at 192 — 2.6x the realistic corpus's longest URI — and
# the CSR_SLOTS_MAX regrow at 1536, past any padded line bucket.
URI_WINDOW_PER_SLOT = 12

# row 0 bit assignments (see compute_rows): bit 0 = line validity, bit 1 =
# plausibility (multi-format winner protocol), bit 2 = CSR slot overflow,
# bit 3 = the valid line's quoted-field split consumed a backslash-escaped
# separator occurrence (escape-parity masking — the device handled a line
# that pre-round-18 routed to the host rescue).
CSR_OVERFLOW_BIT = 4
ESC_QUOTE_BIT = 8


def csr_group_key(plan: FieldPlan) -> str:
    """All qscsr plans over the same token+steps+mode share one segment
    table (mode — query vs cookie — picks the separator)."""
    return f"@qs:{plan.token_index}:{plan.meta}:{plan.steps!r}"


_CSR_SEPARATORS = {"query": b"&", "cookie": b"; "}


def geo_group_key(plan: FieldPlan) -> str:
    """All geo plans over the same token+steps+database share one device
    range-join (plan.meta = (tag, column, GeoDeviceTable); the tag is the
    pickle-stable database identity)."""
    return f"@geo:{plan.token_index}:{plan.meta[0]}:{plan.steps!r}"


def muid_group_key(plan: FieldPlan) -> str:
    """All mod_unique_id plans over the same token+steps share one decode."""
    return f"@muid:{plan.token_index}:{plan.steps!r}"


@dataclass
class PackedLayout:
    """Bit-slot map for the packed [K, B] int32 output (row 0 = validity).

    Timestamp component bundles are shared: every ``ts`` plan on the same
    (token, steps) maps to one ``@ts:...`` slot group with rows
    ``c1`` (year|month|day|hour), ``c2`` (minute|second|milli), ``off``
    (raw UTC offset seconds) and an ``ok`` bit.
    """

    slots: Dict[str, Dict[str, Slot]] = dataclass_field(default_factory=dict)
    n_rows: int = 1
    csr_slots: int = CSR_SLOTS

    @classmethod
    def for_plans(
        cls, plans: Sequence[FieldPlan], csr_slots: int = CSR_SLOTS
    ) -> "PackedLayout":
        layout = cls(csr_slots=csr_slots)
        aux_needs: List[Tuple[str, str, int]] = []  # (slot_key, comp, bits)
        for plan in plans:
            kind = plan.kind
            if kind == "host":
                continue
            if kind in ("span", "ulist"):
                r = layout.n_rows
                layout.n_rows += 1
                layout.slots[plan.field_id] = {
                    "start": (r, 0, _SPAN_BITS),
                    "len": (r, _SPAN_BITS, _SPAN_BITS),
                    "ok": (r, 2 * _SPAN_BITS, 1),
                    # null: the value is absent/None (CLF '-' on direct
                    # token captures; undelivered URI parts).  amp: the
                    # span's leading '?' renders as '&' (query
                    # normalization).  fix: the row needs per-row host
                    # micro-materialization (%-repair / path decode).
                    "null": (r, 2 * _SPAN_BITS + 1, 1),
                    "amp": (r, 2 * _SPAN_BITS + 2, 1),
                    "fix": (r, 2 * _SPAN_BITS + 3, 1),
                }
            elif kind in ("long", "secmillis"):
                rhi, rlo = layout.n_rows, layout.n_rows + 1
                layout.n_rows += 2
                layout.slots[plan.field_id] = {
                    "hi": (rhi, 0, 0),
                    "lo": (rlo, 0, 0),
                }
                aux_needs += [
                    (plan.field_id, "ok", 1),
                    (plan.field_id, "null", 1),
                    (plan.field_id, "lo_digits", 5),  # digit count <= 19
                    (plan.field_id, "d18", 4),        # the 19th frame digit
                    # >19-digit run, device-valid: the hi row carries
                    # start|len<<_SPAN_BITS for the host byte-patch.
                    (plan.field_id, "big", 1),
                ]
                if kind == "secmillis":
                    aux_needs.append((plan.field_id, "milli", 10))
            elif kind == "ts":
                key = ts_group_key(plan)
                if key not in layout.slots:
                    r = layout.n_rows
                    layout.n_rows += 3
                    layout.slots[key] = {
                        "c1": (r, 0, 0),
                        "c2": (r + 1, 0, 0),
                        "off": (r + 2, 0, 0),
                    }
                    aux_needs.append((key, "ok", 1))
            elif kind == "geo":
                key = geo_group_key(plan)
                if key not in layout.slots:
                    r = layout.n_rows
                    layout.n_rows += 1
                    layout.slots[key] = {"row": (r, 0, 0)}
                    aux_needs.append((key, "ok", 1))
            elif kind == "muid":
                key = muid_group_key(plan)
                if key not in layout.slots:
                    r = layout.n_rows
                    layout.n_rows += 4
                    layout.slots[key] = {
                        "time": (r, 0, 0),
                        "ip": (r + 1, 0, 0),
                        "pid": (r + 2, 0, 0),
                        "thread": (r + 3, 0, 0),
                    }
                    aux_needs += [(key, "ok", 1), (key, "counter", 16)]
            elif kind == "qscsr":
                key = csr_group_key(plan)
                if key not in layout.slots:
                    slots: Dict[str, Slot] = {}
                    for k in range(csr_slots):
                        rn = layout.n_rows
                        rv = layout.n_rows + 1
                        layout.n_rows += 2
                        slots[f"s{k}_start"] = (rn, 0, _SPAN_BITS)
                        slots[f"s{k}_nlen"] = (rn, _SPAN_BITS, _SPAN_BITS)
                        slots[f"s{k}_eq"] = (rn, 2 * _SPAN_BITS, 1)
                        slots[f"s{k}_dec"] = (rn, 2 * _SPAN_BITS + 1, 1)
                        slots[f"s{k}_ndec"] = (rn, 2 * _SPAN_BITS + 2, 1)
                        slots[f"s{k}_nhigh"] = (rn, 2 * _SPAN_BITS + 3, 1)
                        slots[f"s{k}_vstart"] = (rv, 0, _SPAN_BITS)
                        slots[f"s{k}_vlen"] = (rv, _SPAN_BITS, _SPAN_BITS)
                    layout.slots[key] = slots
                    aux_needs.append((key, "ok", 1))
            else:  # pragma: no cover
                raise AssertionError(kind)
        # Pack aux bits into shared meta rows (30 usable bits per row: the
        # sign bit stays clear and decoding needs no sign games).
        shift = 30
        row = layout.n_rows - 1
        for fid, comp, bits in aux_needs:
            if shift + bits > 30:
                row = layout.n_rows
                layout.n_rows += 1
                shift = 0
            layout.slots.setdefault(fid, {})[comp] = (row, shift, bits)
            shift += bits
        return layout

    # -- host-side decode ------------------------------------------------

    def get(self, packed: np.ndarray, field_id: str, comp: str) -> np.ndarray:
        """Decode one component from the packed [K, B] host array."""
        row, shift, bits = self.slots[field_id][comp]
        col = packed[row]
        if bits == 0:
            return col
        return (col >> shift) & ((1 << bits) - 1)

    def get_ts_components(self, packed: np.ndarray, plan: FieldPlan):
        """Decode a ts plan's shared component bundle -> (components, ok).

        Bit layout written by compute_rows: c1 = year | month<<14 | day<<18
        | hour<<23; c2 = minute | second<<6 | milli<<12; off = raw int32.
        """
        key = ts_group_key(plan)
        c1 = self.get(packed, key, "c1")
        c2 = self.get(packed, key, "c2")
        comp = {
            "year": (c1 & 0x3FFF).astype(np.int64),
            "month": ((c1 >> 14) & 0xF).astype(np.int64),
            "day": ((c1 >> 18) & 0x1F).astype(np.int64),
            "hour": ((c1 >> 23) & 0x1F).astype(np.int64),
            "minute": (c2 & 0x3F).astype(np.int64),
            "second": ((c2 >> 6) & 0x3F).astype(np.int64),
            "milli": ((c2 >> 12) & 0x3FF).astype(np.int64),
            "offset_seconds": self.get(packed, key, "off").astype(np.int64),
        }
        ok = self.get(packed, key, "ok") != 0
        return comp, ok


def span_prefix_words(
    b32: jnp.ndarray,
    s: jnp.ndarray,
    e: jnp.ndarray,
    ok: jnp.ndarray,
    null: Optional[jnp.ndarray],
    amp: Optional[jnp.ndarray],
    extract,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """LE-packed first-12-byte words of one span field, computed IN the
    unit pass (bytes masked beyond len; dead rows all-zero).  Gathering
    here — where the split/chain stages are already streaming the byte
    buffer — folds the view-prefix extraction into the same fusion
    cluster; the pre-round-6 post-merge gather depended on every unit's
    packed rows, so XLA had to re-stream the whole [B, L] buffer in a
    separate HBM sweep per view field.  The '?'->'&' query normalization
    is rendered in place so <= 12-byte amp values need no host patching."""
    length = e - s
    live = ok if null is None else (ok & ~null)
    first12 = extract(b32, s, 12)
    pos = jnp.arange(12, dtype=jnp.int32)[None, :]
    masked = jnp.where(
        live[:, None] & (pos < length[:, None]),
        first12.astype(jnp.int32),
        0,
    )
    if amp is not None:
        amp_row = amp & live & (length > 0) & (masked[:, 0] == ord("?"))
        masked = masked.at[:, 0].set(
            jnp.where(amp_row, ord("&"), masked[:, 0])
        )
    words = []
    for w in range(3):
        b = masked[:, 4 * w: 4 * w + 4]
        words.append(
            (b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16)
             | (b[:, 3] << 24)).astype(jnp.int32)
        )
    return words[0], words[1], words[2]


def compute_rows(
    program: DeviceProgram,
    plans: Sequence[FieldPlan],
    layout: PackedLayout,
    b32: jnp.ndarray,
    lengths: jnp.ndarray,
    need_plausible: bool = False,
    view_fields: Sequence[str] = (),
) -> Tuple[List[jnp.ndarray], Dict[str, Tuple[jnp.ndarray, ...]]]:
    """The fused computation: split + per-plan post-stages -> K rows of [B]
    int32 (row 0: bit 0 = line validity, bit 1 = plausibility when
    requested).  Returns (rows, view_prefix): the executor stacks the
    rows; ``view_prefix`` maps each requested ``view_fields`` span field
    to its 3 LE-packed first-12-byte words (see span_prefix_words),
    consumed by the winner merge in :func:`compute_view_rows`."""
    B = b32.shape[0]
    starts, ends, valid, plausible, esc_hit = compute_split(
        program, b32, lengths, need_plausible
    )
    extract = postproc.gather_span_bytes

    rows: List[Optional[jnp.ndarray]] = [None] * layout.n_rows
    view_set = frozenset(view_fields)
    view_prefix: Dict[str, Tuple[jnp.ndarray, ...]] = {}

    def put(fid: str, comp: str, val: jnp.ndarray) -> None:
        row, shift, bits = layout.slots[fid][comp]
        v = val.astype(jnp.int32)
        if bits:
            v = (v & ((1 << bits) - 1)) << shift
        rows[row] = v if rows[row] is None else (rows[row] | v)

    def put_span(fid: str, s, e, ok, null=None, amp=None, fix=None) -> None:
        put(fid, "start", s)
        put(fid, "len", e - s)
        put(fid, "ok", jnp.where(ok, 1, 0))
        if null is not None:
            put(fid, "null", jnp.where(null, 1, 0))
        if amp is not None:
            put(fid, "amp", jnp.where(amp, 1, 0))
        if fix is not None:
            put(fid, "fix", jnp.where(fix, 1, 0))
        if fid in view_set:
            view_prefix[fid] = span_prefix_words(
                b32, s, e, ok, null, amp, extract
            )

    # ---- span-transform chains (device sub-dissectors) ----------------
    # chain(token, steps) -> (start, end, ok, null, amp); each prefix is
    # computed once.  Steps may also constrain LINE validity (a URI the
    # repair chain would rewrite must send the whole line to the oracle,
    # which re-applies the exact repair semantics).
    fl_cache: Dict[tuple, Dict[str, jnp.ndarray]] = {}
    uri_cache: Dict[tuple, Dict[str, jnp.ndarray]] = {}
    pv_cache: Dict[tuple, Dict[str, jnp.ndarray]] = {}
    chain_cache: Dict[tuple, tuple] = {}
    line_constraints: List[jnp.ndarray] = []
    csr_overflow_rows: List[jnp.ndarray] = []
    false_b = jnp.zeros(B, dtype=bool)
    # Authority reductions (userinfo/host/port) only run when some plan
    # actually delivers those parts — path/query-only workloads skip them.
    need_authority = any(
        ("uri", part) in plan.steps
        for plan in plans
        for part in ("host", "userinfo", "port")
    )

    def clf_dash(s, e):
        """Token-level CLF null: the span is a lone '-'
        (decode_extracted_value, ApacheHttpdLogFormatDissector:176-178 /
        NginxHttpdLogFormatDissector:107-119)."""
        first = extract(b32, s, 1)[:, 0]
        return ((e - s) == 1) & (first == np.uint8(ord("-")))

    def run_step(step: Tuple[str, str], s, e, ok, cache_key):
        name, part = step
        if name == "fl":
            fl = fl_cache.get(cache_key)
            if fl is None:
                fl = postproc.split_firstline(
                    b32, lengths, s, e, extract=extract
                )
                fl_cache[cache_key] = fl
            if part == "protocol":
                step_ok = fl["ok"] & fl["has_protocol"]
                return (fl["proto_start"], fl["proto_end"], ok & step_ok,
                        false_b, false_b, false_b)
            return (
                fl[f"{part}_start"], fl[f"{part}_end"], ok & fl["ok"],
                false_b, false_b, false_b,
            )
        if name == "pv":
            pv = pv_cache.get(cache_key)
            if pv is None:
                # Direct token input: CLF '-' is null (the dissector's
                # early return).  Sub-spans (firstline protocol) cannot be
                # a lone dash — the fl split already requires "HTTP/".
                dash = clf_dash(s, e) if len(cache_key) == 1 else None
                pv = postproc.split_protocol_version(b32, s, e, dash=dash)
                pv_cache[cache_key] = pv
            if part == "protocol":
                return (s, pv["proto_end"], ok, pv["null"], false_b, false_b)
            return (
                pv["ver_start"], pv["ver_end"], ok, pv["null"],
                false_b, false_b,
            )
        if name == "uri":
            uri = uri_cache.get(cache_key)
            if uri is None:
                # Direct token input: CLF null — the dissector receives
                # None and delivers nothing.  Sub-spans (firstline uri)
                # take '-' literally, like the host.
                dash = clf_dash(s, e) if len(cache_key) == 1 else None
                uri = postproc.split_uri_fast(
                    b32, s, e, extract=extract, dash=dash,
                    need_authority=need_authority,
                    window=URI_WINDOW_PER_SLOT * layout.csr_slots,
                )
                uri_cache[cache_key] = uri
                # Repair-needing URIs fail the line (unless the chain
                # already produced nothing to repair).
                line_constraints.append(uri["ok"] | ~ok)
                # Span longer than the scan window: the same capacity
                # defer as CSR slot exhaustion — raise the overflow bit
                # (adaptive slot growth scales the window along) and
                # fail the line so it rides the batched rescue.
                uri_over = uri["overflow"] & ok
                csr_overflow_rows.append(uri_over)
                line_constraints.append(~uri_over)
            step_ok = ok & uri["ok"]
            if part == "path":
                return (
                    uri["path_start"], uri["path_end"], step_ok,
                    uri["path_null"], false_b, uri["path_fix"],
                )
            if part == "query":
                return (
                    uri["query_start"], uri["query_end"], step_ok,
                    uri["query_null"], uri["query_amp"], uri["query_fix"],
                )
            if part == "protocol":
                return (
                    uri["proto_start"], uri["proto_end"], step_ok,
                    uri["proto_null"], false_b, false_b,
                )
            if part == "userinfo":
                return (
                    uri["userinfo_start"], uri["userinfo_end"], step_ok,
                    uri["userinfo_null"], false_b, uri["userinfo_fix"],
                )
            if part == "host":
                return (
                    uri["host_start"], uri["host_end"], step_ok,
                    uri["host_null"], false_b, false_b,
                )
            if part == "port":
                # Null port == empty span: the downstream long parse fails
                # on it and the column reads None (the host only delivers
                # port when the authority parse produced one).
                return (
                    uri["port_start"], uri["port_end"], step_ok,
                    false_b, false_b, false_b,
                )
            # ref: clean rows cannot contain '#', so the fragment is
            # always absent -> null span.
            return s, s, step_ok, jnp.ones(B, dtype=bool), false_b, false_b
        raise AssertionError(step)  # pragma: no cover

    def chain_spans(token_index: int, steps):
        key = (token_index, steps)
        got = chain_cache.get(key)
        if got is not None:
            return got
        if steps:
            s, e, ok, _, _, _ = chain_spans(token_index, steps[:-1])
            s, e, ok, null, amp, fix = run_step(
                steps[-1], s, e, ok, key[:1] + steps[:-1]
            )
        else:
            s, e = starts[token_index], ends[token_index]
            ok = jnp.ones(B, dtype=bool)
            null = amp = fix = None
        chain_cache[key] = (s, e, ok, null, amp, fix)
        return s, e, ok, null, amp, fix

    group_done = set()  # emitted shared groups (@ts:/@qs: keys)
    for plan in plans:
        if plan.kind == "host":
            continue
        s, e, chain_ok, null, amp, fix = chain_spans(plan.token_index, plan.steps)
        if plan.kind == "span":
            if not plan.steps:
                null = clf_dash(s, e)  # direct token capture: CLF null
            put_span(plan.field_id, s, e, chain_ok, null, amp, fix)
        elif plan.kind in ("long", "secmillis"):
            big = None
            if plan.kind == "secmillis":
                (hi, lo, d18, lo_digits), milli, is_null, ok = (
                    postproc.parse_secmillis_spans(b32, s, e, extract=extract)
                )
                put(plan.field_id, "milli", milli)
            else:
                (hi, lo, d18, lo_digits), is_null, ok, big = (
                    postproc.parse_long_spans(
                        b32, s, e,
                        clf=plan.null_mode in ("dash_null", "dash_zero"),
                        extract=extract,
                    )
                )
            # Full-int64 overflow handling is only wired for the PLAIN
            # direct-token long (the %b/%D FORMAT_NUMBER class): scaled
            # values, zero_null (string-compared) conversions and chained
            # sub-spans keep their pre-widening behavior — decode failure
            # routes the line to the oracle, whose semantics are exact.
            allow_big = (
                plan.kind == "long"
                and not plan.steps
                and plan.scale == 1
                and plan.null_mode != "zero_null"
            )
            if big is not None and allow_big:
                # Device-valid >19-digit runs: the frame cannot carry the
                # value, so the hi row carries the span instead and the
                # host patches the exact value from the byte buffer
                # (reference Long-overflow semantics; only the first 19
                # bytes were digit-checked — the patch checks the rest).
                blen = jnp.minimum(e - s, (1 << _SPAN_BITS) - 1)
                hi = jnp.where(big, s | (blen << _SPAN_BITS), hi)
                lo = jnp.where(big, 0, lo)
                d18 = jnp.where(big, 0, d18)
                put(plan.field_id, "big", jnp.where(big, 1, 0))
            elif big is not None:
                ok = ok & ~big
                put(plan.field_id, "big", jnp.zeros_like(hi))
            else:
                put(plan.field_id, "big", jnp.zeros_like(hi))
            put(plan.field_id, "hi", hi)
            put(plan.field_id, "lo", lo)
            put(plan.field_id, "d18", d18)
            put(plan.field_id, "lo_digits", lo_digits)
            put(plan.field_id, "ok", jnp.where(ok, 1, 0))
            put(plan.field_id, "null", jnp.where(is_null, 1, 0))
            if not plan.steps:
                # Direct token numerics: the split charset admitted the
                # span, so a decode failure (non-digit window bytes,
                # malformed sec.millis, >19-digit runs outside the
                # allow_big class) is exactly a case the host path types
                # differently or rejects — route the line to the oracle.
                valid = valid & (ok | ~chain_ok)
            if plan.null_mode == "zero_null":
                # ConvertNumberIntoCLF compares the STRING to "0": a span
                # with leading zeros ("00", "007") passes through verbatim
                # on the host, which the int64 column cannot represent —
                # those rows go to the oracle.  After this exclusion,
                # value==0 is exactly span=="0".
                first = extract(b32, s, 1)[:, 0]
                leading_zero = ((e - s) > 1) & (first == np.uint8(ord("0")))
                valid = valid & ~(leading_zero & chain_ok)
        elif plan.kind == "geo":
            key = geo_group_key(plan)
            if key in group_done:
                continue
            group_done.add(key)
            table = plan.meta[2]
            u32, ip_ok, has_colon = postproc.parse_ipv4_spans(
                b32, s, e, extract=extract
            )
            rows_idx = table.lookup_rows(u32)
            put(key, "row", jnp.where(ip_ok & chain_ok, rows_idx, 0))
            put(key, "ok", jnp.where(chain_ok, 1, 0))
            # IPv6 literals: the host DOES look them up in the trie; the
            # flattened device table is IPv4-only, so those lines take the
            # oracle.
            valid = valid & ~(has_colon & chain_ok)
        elif plan.kind == "ulist":
            # Indexed nginx upstream-list element.  The list token's
            # NARROW charset excludes every separator and whitespace byte,
            # so a device-valid row is necessarily a SINGLE untrimmable
            # element: element 0 (value and redirected alike) is the token
            # span itself, any higher index is absent.  Multi-element and
            # redirect lists contain charset-rejected bytes and take the
            # oracle, which indexes them exactly.
            u_idx, _u_which = plan.meta
            u_dash = clf_dash(s, e) if not plan.steps else false_b
            if u_idx == 0:
                put_span(plan.field_id, s, e, chain_ok & ~u_dash)
            else:
                put_span(plan.field_id, s, s, jnp.zeros(B, dtype=bool))
        elif plan.kind == "muid":
            key = muid_group_key(plan)
            if key in group_done:
                continue
            group_done.add(key)
            words, ok = postproc.parse_mod_unique_id(
                b32, s, e, extract=extract
            )
            for comp in ("time", "ip", "pid", "thread"):
                put(key, comp, words[comp])
            put(key, "counter", words["counter"])
            put(key, "ok", jnp.where(ok & chain_ok, 1, 0))
            # A non-decodable token just delivers nothing on the host
            # (no line failure) — `valid` is untouched.
        elif plan.kind == "qscsr":
            key = csr_group_key(plan)
            if key in group_done:
                continue
            group_done.add(key)
            if plan.meta == "setcookie":
                if not plan.steps:
                    chain_ok = chain_ok & ~clf_dash(s, e)
                sc = postproc.split_setcookie_csr(
                    b32, s, e, layout.csr_slots,
                )
                for k in range(layout.csr_slots):
                    seg_s = sc["seg_start"][k]
                    seg_e = sc["seg_end"][k]
                    emit = sc["emit"][k]
                    put(key, f"s{k}_start", jnp.where(emit, seg_s, 0))
                    put(key, f"s{k}_nlen",
                        jnp.where(emit, sc["name_end"][k] - seg_s, 0))
                    put(key, f"s{k}_eq", jnp.where(emit, 1, 0))
                    put(key, f"s{k}_vstart", jnp.where(emit, seg_s, 0))
                    put(key, f"s{k}_vlen", jnp.where(emit, seg_e - seg_s, 0))
                put(key, "ok", jnp.where(chain_ok, 1, 0))
                # Host-quirk rows (overwritten held part, set-cookie:
                # prefix) and slot overflow take the oracle.  The overflow
                # bit is masked by the running line validity: overflow on
                # an already-rejected line must not trigger slot growth.
                valid = valid & ~(sc["bad"] & chain_ok)
                overflowed = sc["overflow"] & chain_ok & valid
                valid = valid & ~overflowed
                csr_overflow_rows.append(overflowed)
                continue
            if plan.steps and plan.steps[-1] == ("uri", "query"):
                # The uri query span keeps its leading '?' (rendered '&'
                # by the normalization); as QueryStringFieldDissector
                # input that first separator only produces an empty
                # segment the host skips — start the split past it.
                first = extract(b32, s, 1)[:, 0]
                s = jnp.where(
                    (s < e) & (first == np.uint8(ord("?"))), s + 1, s
                )
            csr = postproc.split_csr(
                b32, s, e, layout.csr_slots,
                sep=_CSR_SEPARATORS[plan.meta or "query"],
                # URI-chained query strings pass through the URI encode
                # step before the host dissector sees them — encode-set
                # bytes flag the per-row path.  Direct token captures
                # (nginx $args) and cookies are raw header text: no.
                uri_encoded=bool(plan.steps) and plan.steps[-1][0] == "uri",
                window=CSR_WINDOW_PER_SLOT * layout.csr_slots,
            )
            if not plan.steps:
                # Direct token capture of the query string: CLF null ->
                # no params delivered.
                chain_ok = chain_ok & ~clf_dash(s, e)
            for k in range(layout.csr_slots):
                seg_s = csr["seg_start"][k]
                seg_e = csr["seg_end"][k]
                eq = csr["eq_pos"][k]
                seg_empty = seg_s >= seg_e
                nlen = jnp.where(seg_empty, 0, eq - seg_s)
                has_eq = (~seg_empty) & (eq < seg_e)
                vstart = jnp.minimum(eq + 1, seg_e)
                vlen = jnp.where(has_eq, seg_e - vstart, 0)
                put(key, f"s{k}_start", jnp.where(seg_empty, 0, seg_s))
                put(key, f"s{k}_nlen", nlen)
                put(key, f"s{k}_eq", jnp.where(has_eq, 1, 0))
                put(key, f"s{k}_dec", jnp.where(csr["decode"][k], 1, 0))
                put(key, f"s{k}_ndec", jnp.where(csr["name_pct"][k], 1, 0))
                put(key, f"s{k}_nhigh", jnp.where(csr["name_high"][k], 1, 0))
                put(key, f"s{k}_vstart", jnp.where(has_eq, vstart, 0))
                put(key, f"s{k}_vlen", vlen)
            put(key, "ok", jnp.where(chain_ok, 1, 0))
            # More segments than slots: the oracle takes the whole line,
            # and the overflow is surfaced in row 0 so the host can react
            # by growing the slot count (adaptive CSR).  Masked by the
            # running line validity so overflow on an already-rejected
            # line cannot trigger permanent slot growth.
            overflowed = csr["overflow"] & chain_ok & valid
            valid = valid & ~overflowed
            csr_overflow_rows.append(overflowed)
        elif plan.kind == "ts":
            if ts_group_key(plan) in group_done:
                continue
            group_done.add(ts_group_key(plan))
            comp, ok = timeparse.parse_device_timestamp(
                b32, s, e, plan.meta, extract
            )
            key = ts_group_key(plan)
            put(key, "c1",
                comp["year"] | (comp["month"] << 14) | (comp["day"] << 18)
                | (comp["hour"] << 23))
            put(key, "c2",
                comp["minute"] | (comp["second"] << 6) | (comp["milli"] << 12))
            put(key, "off", comp["offset_seconds"])
            put(key, "ok", jnp.where(ok, 1, 0))
            # A timestamp the host layout rejects raises DissectionFailure
            # there, failing the whole line — mirror that: route the line
            # to the oracle (which will reject it identically).
            valid = valid & (ok | ~chain_ok)
        else:  # pragma: no cover
            raise AssertionError(plan.kind)

    for constraint in line_constraints:
        valid = valid & constraint
    row0 = jnp.where(valid, 1, 0).astype(jnp.int32)
    if plausible is not None:
        row0 = row0 | (jnp.where(plausible, 2, 0).astype(jnp.int32))
    if esc_hit is not None:
        # Escaped-quote decode marker: only meaningful on lines this
        # format still claims after every constraint (the host counts
        # device_escaped_quote_lines_total from the winning unit's bit).
        row0 = row0 | jnp.where(
            esc_hit & valid, ESC_QUOTE_BIT, 0
        ).astype(jnp.int32)
    for overflowed in csr_overflow_rows:
        row0 = row0 | jnp.where(overflowed, CSR_OVERFLOW_BIT, 0).astype(
            jnp.int32
        )
    rows[0] = row0
    zero = jnp.zeros(B, dtype=jnp.int32)
    return [r if r is not None else zero for r in rows], view_prefix


# ---------------------------------------------------------------------------
# Entry points: the jnp executor of the packed pipeline.
#
# Multi-format (SURVEY §7.7): the reference keeps ONE active format and
# switches on DissectionFailure (HttpdLogFormatDissector.java:174-204) — a
# stateful, path-dependent scheme.  The vectorized equivalent runs EVERY
# registered format's split automaton over the batch in the same fused
# computation and picks the per-line winner by registration priority
# (deterministic, order-independent — strictly better than active/fallback).
# Each format is one FormatUnit; its rows are stacked into one [sum K_i, B]
# packed output, so multi-format still costs exactly one device->host fetch.
# ---------------------------------------------------------------------------


@dataclass
class FormatUnit:
    """One registered LogFormat's compiled device pipeline: split program +
    per-field plans + packed row layout.  row_offset is its first row in the
    stacked multi-format output (row row_offset = this format's validity)."""

    program: DeviceProgram
    plans: List[FieldPlan]
    layout: PackedLayout
    row_offset: int = 0
    # True for an uncompilable format's separator-order probe
    # (compile_plausibility_program): its single row carries ONLY the
    # plausibility bit — the valid bit stays 0, so it can never claim a
    # line, only contest later formats' claims.
    plausibility_only: bool = False

    def plan_for(self, field_id: str) -> FieldPlan:
        for p in self.plans:
            if p.field_id == field_id:
                return p
        return FieldPlan(field_id, "host")


def assign_row_offsets(units: Sequence[FormatUnit]) -> int:
    """Set each unit's row_offset; returns the stacked row count K."""
    off = 0
    for u in units:
        u.row_offset = off
        off += u.layout.n_rows
    return off


def packed_row_count(units: Sequence[FormatUnit]) -> int:
    """Stacked packed-output rows of one executor pass over ``units``
    (``assign_row_offsets``'s return value without mutating offsets) —
    the single home of the D2H footprint arithmetic the device byte
    budget reads."""
    return sum(u.layout.n_rows for u in units)


def estimate_device_bytes(
    units: Sequence[FormatUnit],
    n_view_fields: int,
    padded_b: int,
    line_len: int,
    lengths_itemsize: int = 4,
    aggregate_group_ops: Optional[int] = None,
) -> int:
    """Pre-allocation device-footprint estimate for one padded batch:
    the staged H2D input (``[padded_b, line_len]`` uint8 buffer + the
    lengths vector) plus the packed int32 verdict output (one row per
    output component, 4 trailing rows per device-view span field) —
    deliberately the same arithmetic the executor's buffers resolve to,
    so a budget validated against this estimate is a budget the device
    actually sees (docs/FAULTS.md; the batch-tier twin of the serving
    tier's frame ceilings validated before allocation).

    ``aggregate_group_ops`` switches to the analytics-pushdown footprint
    (docs/ANALYTICS.md): the reduction emits no device-view rows and no
    packed-column D2H — its resident peak is the units rows (the parse
    intermediates, before XLA prunes the unread ones) plus the sort
    workspace of the grouping ops (five int32 key/operand lanes each,
    double-buffered by ``lax.sort``).  Without this split, the budget
    charged aggregate batches the full view-emitting row-path footprint
    and over-rejected batches that fit comfortably."""
    rows = packed_row_count(units)
    if aggregate_group_ops is None:
        rows += 4 * int(n_view_fields)
    else:
        rows += 10 * int(aggregate_group_ops)
    input_bytes = padded_b * line_len + padded_b * lengths_itemsize
    return int(input_bytes + rows * padded_b * 4)


def _units_rows_and_prefixes(
    units: Sequence[FormatUnit],
    buf: jnp.ndarray,
    lengths: jnp.ndarray,
    view_specs: Sequence[Tuple[str, Sequence[int]]] = (),
) -> Tuple[List[jnp.ndarray], Dict[Tuple[int, str], Tuple[jnp.ndarray, ...]]]:
    """All formats' packed rows for one batch, plus — when ``view_specs``
    names (field, unit) pairs — each unit's in-pass first-12-byte view
    prefix words, keyed (unit_index, field_id)."""
    rows: List[jnp.ndarray] = []
    prefixes: Dict[Tuple[int, str], Tuple[jnp.ndarray, ...]] = {}
    for ui, u in enumerate(units):
        # Plausibility is computed for EVERY unit (not just non-final
        # ones): besides the multi-format winner contest, the host uses
        # "implausible for all formats" as a sound definitely-bad filter —
        # regex-accept implies plausible, so such lines skip the per-line
        # oracle re-parse entirely.
        if u.plausibility_only:
            # Uncompilable format: one row, plausible bit only (bit 1);
            # the valid bit is never set so the probe cannot win a line.
            _, _, _, plausible, _ = compute_split(
                u.program, buf, lengths, need_plausible=True
            )
            rows.append(jnp.where(plausible, 2, 0).astype(jnp.int32))
            continue
        vf = [fid for fid, unit_idx in view_specs if ui in unit_idx]
        unit_rows, unit_prefix = compute_rows(
            u.program, u.plans, u.layout, buf, lengths,
            need_plausible=True, view_fields=vf,
        )
        rows.extend(unit_rows)
        for fid, words in unit_prefix.items():
            prefixes[(ui, fid)] = words
    return rows, prefixes


def compute_units_rows(
    units: Sequence[FormatUnit],
    buf: jnp.ndarray,
    lengths: jnp.ndarray,
) -> List[jnp.ndarray]:
    """All formats' packed rows for one batch — the single executor body
    shared by the jnp path (via :func:`units_fn`), the mesh runners, and
    bench.py.  Every compare and range check is correct under both uint8
    and int32 inputs: uint8 wraparound "negatives" land >= 230 and int32
    gives true negatives, and each fails the <= 9 / < 26 digit and letter
    range checks identically (the timestamp parser digit-checks every
    numeric byte explicitly for exactly this reason)."""
    rows, _ = _units_rows_and_prefixes(units, buf, lengths)
    return rows


def units_fn(units: Sequence[FormatUnit]):
    """The un-jitted plain-XLA executor body over all formats:
    (buf [B,L] uint8, lengths [B]) -> [sum K_i, B] int32.  The single
    source for build_units_jnp_fn and the sharded mesh runners."""

    def fn(buf: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        # buf stays uint8 end-to-end here: the [B, L] passes are HBM-bound
        # and every compare works on uint8 directly — an int32 up-cast
        # would 4x the traffic.
        return jnp.stack(compute_units_rows(units, buf, lengths))

    return fn


# Device-emitted Arrow view ingredients: 4 extra int32 rows per span field
# appended to the packed output.  Row 0 is the winner-merged span word
# (start | len<<13 | live<<26); rows 1-3 carry the span's first 12 bytes
# (LE-packed, masked beyond len).  The host turns these into Arrow
# string_view structs with one streaming interleave pass
# (native lp_views_interleave) instead of re-streaming the whole [B, L]
# buffer — on the 1-core bench host the byte gather runs at ~6.7 GB/s,
# on the TPU at HBM speed.  The prefix bytes themselves are extracted
# inside each unit's pass (span_prefix_words) and only winner-SELECTED
# here, so view emission adds [B]-shaped selects, not buffer sweeps.
VIEW_ROWS_PER_FIELD = 4
VIEW_LEN_SHIFT = _SPAN_BITS
VIEW_LIVE_SHIFT = 2 * _SPAN_BITS


def compute_view_rows(
    units: Sequence[FormatUnit],
    rows: List[jnp.ndarray],
    view_specs: Sequence[Tuple[str, Sequence[int]]],
    prefixes: Dict[Tuple[int, str], Tuple[jnp.ndarray, ...]],
) -> List[jnp.ndarray]:
    """Winner-merged Arrow view rows for span fields, computed ON DEVICE.

    ``rows`` is the flat list of all units' packed rows (pre-stack);
    ``view_specs`` is [(field_id, [unit_index, ...])] listing, per span
    field, the units the host would decode it from (``_unit_decodable``
    semantics — lines won by other units deliver via oracle overrides and
    the host patches their views).  ``prefixes`` carries each unit's
    in-pass first-12-byte words ((unit_index, field_id) ->
    span_prefix_words output); the merge is pure per-line selects.  The
    winner/contested computation mirrors TpuBatchParser._fetch_packed
    exactly."""
    B = rows[0].shape[0]

    # Per-line winner by registration priority + the contested rule (an
    # earlier format still plausible un-claims the line; the host then
    # routes it to the oracle).
    row0 = [rows[u.row_offset] for u in units]
    validity = jnp.stack([(r & 1) for r in row0])          # [U, B]
    plausible = jnp.stack([((r >> 1) & 1) for r in row0])  # [U, B]
    valid_any = jnp.any(validity != 0, axis=0)
    winner = jnp.argmax(validity, axis=0)
    if len(units) > 1:
        earlier_plausible = jnp.cumsum(plausible, axis=0) - plausible
        # Select-chain instead of take_along_axis: a [U, B] gather lowers
        # to scalar-slow TPU gather ops (+0.18 ms on the 2-unit
        # multiformat config); U is the registered-format count, so U
        # selects are effectively free.
        ep_at_winner = earlier_plausible[0]
        for ui in range(1, len(units)):
            ep_at_winner = jnp.where(
                winner == ui, earlier_plausible[ui], ep_at_winner
            )
        valid_any = valid_any & (ep_at_winner == 0)

    out: List[jnp.ndarray] = []
    zero32 = jnp.zeros(B, dtype=jnp.int32)
    for fid, unit_idx in view_specs:
        merged = zero32
        pwords = [zero32, zero32, zero32]
        for ui in unit_idx:
            u = units[ui]
            r, _, _ = u.layout.slots[fid]["start"]
            w = rows[u.row_offset + r]
            ok = ((w >> (2 * _SPAN_BITS)) & 1) != 0
            null = ((w >> (2 * _SPAN_BITS + 1)) & 1) != 0
            sel = (winner == ui) & valid_any & ok & ~null
            live_word = (w & ((1 << (2 * _SPAN_BITS)) - 1)) | (
                1 << VIEW_LIVE_SHIFT
            )
            merged = jnp.where(sel, live_word, merged)
            unit_words = prefixes[(ui, fid)]
            pwords = [
                jnp.where(sel, unit_words[k], pwords[k]) for k in range(3)
            ]
        out.append(merged)
        out.extend(pwords)
    return out


def units_views_fn(
    units: Sequence[FormatUnit],
    view_specs: Sequence[Tuple[str, Sequence[int]]],
):
    """Executor body emitting packed rows PLUS device view rows:
    [sum K_i + 4 * n_view_fields, B] int32."""

    def fn(buf: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        rows, prefixes = _units_rows_and_prefixes(
            units, buf, lengths, view_specs
        )
        rows.extend(compute_view_rows(units, rows, view_specs, prefixes))
        return jnp.stack(rows)

    return fn


# Tile size for large batches: at 64k x 384 the executor's [B]-shaped
# intermediates overflow fast memory and XLA inserts HBM<->S(1) copies
# that dominate the profile (39.6M lines/s @64k vs 47.2M @16k for the
# same program).  lax.map over 16k tiles keeps each tile's working set
# resident; the per-tile outputs re-pack into the same [K, B] layout.
EXEC_TILE_B = 16384


def build_units_jnp_fn(
    units: Sequence[FormatUnit],
    view_specs: Optional[Sequence[Tuple[str, Sequence[int]]]] = None,
    mesh=None,
):
    """Plain-XLA executor over all formats:
    (buf [B,L] uint8, lengths [B]) -> [sum K_i, B] int32 (plus 4 trailing
    device-view rows per span field when ``view_specs`` is given).

    ``mesh`` (a ``jax.sharding.Mesh`` with a ``data`` axis) lays the
    batch dimension out data-parallel over the mesh's devices via
    ``NamedSharding``/``PartitionSpec`` — the dryrun_multichip /
    batch_parallel_runner machinery promoted to the product hot path.
    The per-line computation has no cross-line dependency, so XLA
    partitions it with zero collectives; output stays the packed
    ``[K, B]`` with the batch column axis sharded, bit-identical to the
    single-device executor (tests/test_parallel.py).  The compile-memory
    tiling below is skipped under a mesh: each device already sees only
    ``B / n_data`` rows, and reshaping a sharded batch axis into tiles
    would force cross-device resharding."""
    fn = (
        units_views_fn(units, view_specs) if view_specs
        else units_fn(units)
    )

    if mesh is not None:
        from ..parallel.mesh import dp_shardings

        in_shardings, out_shardings = dp_shardings(mesh)
        return jax.jit(
            fn, in_shardings=in_shardings, out_shardings=out_shardings
        )

    def tiled(buf: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        B = buf.shape[0]
        if B > EXEC_TILE_B and B % EXEC_TILE_B == 0:
            n = B // EXEC_TILE_B
            tb = buf.reshape(n, EXEC_TILE_B, buf.shape[1])
            tl = lengths.reshape(n, EXEC_TILE_B)
            # Shape probe (traced once, free): rows K + dtype of the
            # packed output for the result allocation.
            probe = jax.eval_shape(fn, tb[0], tl[0])
            K = probe.shape[0]

            def body(i, acc):
                # Write each tile's [K, TILE] block straight into the
                # [K, B] result — no [n, K, TILE] intermediate and no
                # final transpose pass (lax.map needed both).
                tile = fn(
                    jax.lax.dynamic_index_in_dim(tb, i, keepdims=False),
                    jax.lax.dynamic_index_in_dim(tl, i, keepdims=False),
                )
                return jax.lax.dynamic_update_slice(
                    acc, tile, (0, i * EXEC_TILE_B)
                )

            init = jnp.zeros((K, B), dtype=probe.dtype)
            return jax.lax.fori_loop(0, n, body, init)
        return fn(buf, lengths)

    return jax.jit(tiled)
