"""Arrow materialization of batch parse results + IPC interop.

SURVEY §7 step 5: "host materializes Arrow arrays ... Java/any-host interop
over Arrow IPC; sidecar service mode".  The reference has no columnar output
(records go through per-line reflection setters); Arrow is the TPU-native
equivalent of that record-delivery surface: span columns gather straight from
the [B, L] byte buffer into a StringArray, numeric columns become int64 with
a null bitmap, wildcard columns become map<string,string>.

Zero-copy note: device span columns build the StringArray from numpy-gathered
(offsets, bytes) buffers wrapped zero-copy — no per-row Python.  Only the
fallback path (host-override rows, wildcard maps, non-UTF-8 data) goes
through ``to_pylist``'s per-row decode.  Numeric columns are pure numpy.
"""
from __future__ import annotations

import io
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Sequence

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from .batch import BatchResult

# Sentinel from the batched view prefetch: "this column was tried and
# cannot take the view path" — _column_to_arrow goes straight to the
# copy fallback instead of rebuilding the column only to fail again.
_VIEW_FAILED = object()

# Per-vocab Arrow dictionary cache: a production City database holds
# ~1e5 names — rebuilding the pa.string() dictionary per batch would
# out-cost the take() fast path it feeds.  Keyed by id() with the vocab
# object retained (keeps the id stable); live vocabs are few (one per
# mmdb column), but a service that RELOADS its databases would otherwise
# accumulate stale multi-MB entries forever — bound the cache and drop
# the oldest half when it fills (refilling a live vocab is one cheap
# rebuild).
_PA_VOCAB_CACHE: Dict[int, Any] = {}
_PA_VOCAB_CACHE_MAX = 32


def _null_bitmap(valid: np.ndarray):
    """Arrow null-bitmap bytes for a boolean validity vector, or None
    when every row is valid (Arrow's all-valid shorthand).  Single home
    for the little-endian packbits idiom."""
    if valid.all():
        return None
    return np.packbits(valid, bitorder="little")


def _pa_vocab(dvals):
    import pyarrow as pa

    ent = _PA_VOCAB_CACHE.get(id(dvals))
    if ent is None:
        if len(_PA_VOCAB_CACHE) >= _PA_VOCAB_CACHE_MAX:
            for k in list(_PA_VOCAB_CACHE)[: _PA_VOCAB_CACHE_MAX // 2]:
                del _PA_VOCAB_CACHE[k]
        ent = (dvals, pa.array(list(dvals), type=pa.string()))
        _PA_VOCAB_CACHE[id(dvals)] = ent
    return ent[1]



def _spans_to_string_array(
    result: "BatchResult", field_id: str, flat: Optional[Any] = None
) -> Optional[Any]:
    """Vectorized span -> pa.StringArray built on BatchResult.span_bytes
    (the single flat-gather implementation: validity mask, native gather,
    ?&-normalization).  ``flat`` carries a prefetched (data, offsets,
    valid) triple from the batch-wide multi-column gather.  Returns None
    when the column needs the per-row path or the gathered bytes are not
    valid UTF-8."""
    import pyarrow as pa

    B = result.lines_read
    if B == 0:
        return pa.array([], type=pa.string())
    if flat is None:
        flat = result.span_bytes(field_id)
    if flat is None:
        return None
    data, offsets64, valid = flat
    data, offsets64 = _splice_fix_rows(result, field_id, data, offsets64, valid)
    if int(offsets64[-1]) > np.iinfo(np.int32).max:
        # int32 StringArray offsets would wrap; don't rely on validate()
        # catching it after the full gather — take the fallback path now.
        return None
    data = np.ascontiguousarray(data)
    if data.base is not None:
        # A view into the batch-wide multi-column gather buffer: wrapping
        # it zero-copy into the Arrow buffer would pin EVERY span
        # column's bytes for as long as this one column lives.  Copy the
        # column's own bytes (one memcpy, small next to the gather).
        data = data.copy()
    offsets = offsets64.astype(np.int32)
    null_bitmap = np.packbits(valid, bitorder="little")
    # pa.py_buffer wraps the numpy arrays zero-copy (buffer protocol);
    # .tobytes() here would duplicate the data buffer per batch.
    arr = pa.StringArray.from_buffers(
        B,
        pa.py_buffer(offsets),
        pa.py_buffer(data),
        pa.py_buffer(null_bitmap),
    )
    if result.ascii_only:
        # Every source byte is < 0x80, so every gathered span is valid
        # UTF-8 by construction — the per-column validate pass (a third
        # of the column build cost) is provably redundant.
        return arr
    try:
        arr.validate(full=True)  # UTF-8 check happens here
    except pa.ArrowInvalid:
        return None
    return arr


_HEX_VAL = np.full(256, -1, dtype=np.int16)
for _c in b"0123456789":
    _HEX_VAL[_c] = _c - ord("0")
for _c in b"abcdef":
    _HEX_VAL[_c] = _c - ord("a") + 10
for _c in b"ABCDEF":
    _HEX_VAL[_c] = _c - ord("A") + 10
_IS_HEX = _HEX_VAL >= 0
# Printable URI encode-set bytes (postproc.split_uri_fast's `enc`): the
# host %-escapes these before any other repair stage.  Built from the
# host dissector's own constant so device and host cannot drift.
from ..dissectors.uri import ENCODE_PRINTABLE as _ENCODE_PRINTABLE

_IS_ENC = np.zeros(256, dtype=bool)
for _c in _ENCODE_PRINTABLE:
    _IS_ENC[_c] = True
_HEX_UPPER = np.frombuffer(b"0123456789ABCDEF", dtype=np.uint8)


def _repair_fix_segments(seg, seg_off, mode):
    """Vectorized URI repair over concatenated fix-row bytes.

    The repair semantics (%-bad-escape rewrite + path %XX decode,
    HttpUriDissector.java:166-167 / java.net.URI decode) run VECTORIZED
    in fix-row space: rows whose escapes are all well-formed ``%XX``
    decode with numpy scatter/gather; only rows with bad escapes,
    non-ASCII raw bytes, or non-ASCII decode results (UTF-8 replacement
    semantics) take the per-row ``_fix_uri_part`` path.  Returns
    (flat, lens): one repaired value per input row, in order (unchanged
    rows keep their original bytes).  Per-row python values re-encode
    through UTF-8, so they are valid by construction."""
    from .batch import _fix_uri_part

    n_rows = len(seg_off) - 1

    from ..native import copy_spans, repair_spans

    native = repair_spans(seg, seg_off, mode not in ("path", "userinfo"),
                          _IS_ENC)
    if native is not None:
        out_flat, out_lens, py_flags = native
        if not py_flags.any():
            if np.array_equal(out_lens, np.diff(seg_off)):
                # Nothing changed (any real native repair changes a
                # row's length): return the INPUT so callers' identity
                # checks skip their column rebuilds.
                return seg, out_lens
            return out_flat, out_lens
        py_idx = np.nonzero(py_flags)[0]
        py_bytes = [
            _fix_uri_part(
                bytes(seg[seg_off[j]: seg_off[j + 1]]).decode(
                    "utf-8", "replace"), mode,
            ).encode("utf-8")
            for j in py_idx.tolist()
        ]
        out_off = np.zeros(n_rows + 1, dtype=np.int64)
        np.cumsum(out_lens, out=out_off[1:])
        src_base = out_off[:-1].copy()
        new_lens = out_lens.copy()
        base = len(out_flat)
        off = 0
        for j, v in zip(py_idx.tolist(), py_bytes):
            src_base[j] = base + off
            new_lens[j] = len(v)
            off += len(v)
        combined = np.concatenate(
            [out_flat, np.frombuffer(b"".join(py_bytes), dtype=np.uint8)]
        )
        final_off = np.zeros(n_rows + 1, dtype=np.int64)
        np.cumsum(new_lens, out=final_off[1:])
        return copy_spans(combined, src_base, final_off), new_lens

    total = int(seg_off[-1])
    seg_lens = np.diff(seg_off)
    row_id = np.repeat(np.arange(n_rows, dtype=np.int64), seg_lens)

    # Classify every '%' as a well-formed %XX escape or a bad escape
    # (reference _BAD_ESCAPE_PATTERN: % not followed by two hex digits,
    # including at end-of-value).
    nxt1 = np.zeros(total, dtype=np.uint8)
    nxt2 = np.zeros(total, dtype=np.uint8)
    same1 = np.zeros(total, dtype=bool)
    same2 = np.zeros(total, dtype=bool)
    if total > 1:
        nxt1[:-1] = seg[1:]
        same1[:-1] = row_id[1:] == row_id[:-1]
    if total > 2:
        nxt2[:-2] = seg[2:]
        same2[:-2] = row_id[2:] == row_id[:-2]
    pct = seg == ord("%")
    good = pct & same1 & same2 & _IS_HEX[nxt1] & _IS_HEX[nxt2]
    bad = pct & ~good

    def row_any(mask):
        out = np.zeros(n_rows, dtype=bool)
        if mask.any():
            out[np.unique(row_id[mask])] = True
        return out

    # Rows needing the exact per-row semantics: raw non-ASCII bytes (the
    # UTF-8 decode-replace round trip can rewrite invalid sequences) and,
    # in path mode, non-ASCII decode results (multi-escape runs decode as
    # one UTF-8 unit).  Everything else vectorizes:
    # - The reference's TWICE-applied sequential %25 rewrite
    #   (HttpUriDissector.java:166-167) is equivalent to ONE simultaneous
    #   "insert 25 after every originally-bad %": pass-1 consumption can
    #   only defer a bad escape's rewrite to pass 2 (never prevent it),
    #   a rewritten escape is %25-good and never rematched, and no
    #   insertion can land between a good % and its two hex digits.
    # - In path mode, repairing a bad escape then decoding it
    #   (%zz -> %25zz -> %zz) is the identity, so bad escapes simply stay
    #   literal and only good %XX escapes substitute their byte.
    enc = _IS_ENC[seg]
    py_rows = row_any(seg >= 0x80)
    if mode in ("path", "userinfo"):
        # Decoding modes: good %XX escapes substitute their byte; bad
        # escapes stay literal (the %25-repair and the later decode
        # cancel); encode-set bytes are an encode->decode identity.
        dec = ((_HEX_VAL[nxt1] << 4) | np.maximum(_HEX_VAL[nxt2], 0)).astype(
            np.int16
        )
        py_rows |= row_any(good & (dec >= 0x80))
        vec_changed = row_any(good) & ~py_rows
    else:
        # Escaping modes (query): well-formed escapes are untouched; bad
        # escapes gain a '25' insertion and encode-set bytes expand to
        # their uppercase %XX triple.
        vec_changed = row_any(bad | enc) & ~py_rows

    py_idx = np.nonzero(py_rows)[0]
    new_lens = seg_lens.astype(np.int64, copy=True)
    src_base = seg_off[:-1].astype(np.int64, copy=True)
    pieces = [seg]
    if vec_changed.any():
        in_vec = vec_changed[row_id]
        if mode in ("path", "userinfo"):
            # Drop the two hex tail bytes of each good escape, replace
            # the '%' with the decoded byte.
            g = good & in_vec
            tail = np.zeros(total, dtype=bool)
            tail[1:] |= g[:-1]
            tail[2:] |= g[:-2]
            keep = in_vec & ~tail
            new_seg = np.where(g, dec.astype(np.uint8), seg)[keep]
            row_counts = np.bincount(row_id[keep], minlength=n_rows)
        else:
            # Simultaneous bad-escape rewrite + encode: a bad '%' expands
            # to '%25', an encode-set byte to its uppercase '%XX' triple.
            sel = in_vec
            sv = seg[sel]
            bv = (bad & in_vec)[sel]
            ev = (enc & in_vec)[sel]
            rid_v = row_id[sel]
            counts = np.where(bv | ev, 3, 1).astype(np.int64)
            out_pos = np.zeros(sv.size + 1, dtype=np.int64)
            np.cumsum(counts, out=out_pos[1:])
            new_seg = np.repeat(sv, counts)
            ins = out_pos[:-1][bv]
            new_seg[ins + 1] = ord("2")
            new_seg[ins + 2] = ord("5")
            ein = out_pos[:-1][ev]
            new_seg[ein] = ord("%")
            new_seg[ein + 1] = _HEX_UPPER[sv[ev] >> 4]
            new_seg[ein + 2] = _HEX_UPPER[sv[ev] & 0x0F]
            row_counts = np.bincount(
                rid_v, weights=counts, minlength=n_rows
            ).astype(np.int64)
        vloc = np.nonzero(vec_changed)[0]
        voff = np.zeros(vloc.size + 1, dtype=np.int64)
        np.cumsum(row_counts[vloc], out=voff[1:])
        src_base[vloc] = len(seg) + voff[:-1]
        new_lens[vloc] = row_counts[vloc]
        pieces.append(new_seg)
    if py_idx.size:
        py_bytes = [
            _fix_uri_part(
                bytes(seg[seg_off[j] : seg_off[j + 1]]).decode("utf-8", "replace"),
                mode,
            ).encode("utf-8")
            for j in py_idx.tolist()
        ]
        py_buf = np.frombuffer(b"".join(py_bytes), dtype=np.uint8)
        base = sum(len(p) for p in pieces)
        off = 0
        for j, v in zip(py_idx.tolist(), py_bytes):
            src_base[j] = base + off
            new_lens[j] = len(v)
            off += len(v)
        pieces.append(py_buf)

    from ..native import copy_spans

    if len(pieces) == 1:
        return seg, seg_lens.astype(np.int64)
    combined = np.concatenate(pieces)
    out_off = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(new_lens, out=out_off[1:])
    return copy_spans(combined, src_base, out_off), new_lens


def _splice_fix_rows(result: "BatchResult", field_id: str, data, offsets, valid):
    """Patch URI-repair (`fix`) rows into gathered flat span bytes: the
    flat gather copies repair rows RAW; :func:`_repair_fix_segments`
    produces their repaired values, spliced back with the native threaded
    memcpy fan-out."""
    col = result.column(field_id)
    fix = col.get("fix")
    B = result.lines_read
    if fix is None:
        return data, offsets
    rows = np.nonzero(np.asarray(fix[:B], dtype=bool) & valid)[0]
    if rows.size == 0:
        return data, offsets
    lens = np.diff(offsets)
    seg_lens = lens[rows]
    n_rows = rows.size
    seg_off = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(seg_lens, out=seg_off[1:])
    total = int(seg_off[-1])
    idx = np.repeat(offsets[rows] - seg_off[:-1], seg_lens) + np.arange(
        total, dtype=np.int64
    )
    seg = data[idx]
    rep_flat, rep_lens = _repair_fix_segments(seg, seg_off, col["fix_mode"])
    if rep_flat is seg:
        return data, offsets

    from ..native import copy_spans

    rep_off = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(rep_lens, out=rep_off[1:])
    src_base = offsets[:-1].astype(np.int64, copy=True)
    new_lens = lens.astype(np.int64, copy=True)
    src_base[rows] = len(data) + rep_off[:-1]
    new_lens[rows] = rep_lens
    combined = np.concatenate([data, rep_flat])
    new_off = np.zeros_like(offsets)
    np.cumsum(new_lens, out=new_off[1:])
    # Rebuild via the native threaded memcpy fan-out (numpy's per-element
    # fancy-index gather was the splice's hot spot).
    return copy_spans(combined, src_base, new_off), new_off


def _view_column_inputs(result: "BatchResult", field_id: str, buf,
                        base: Optional[Any] = None):
    """Per-column prep for the view materializer: (starts, lens_main,
    state) where state carries everything the assembly step needs.
    ``base`` optionally carries the batched (valid, starts, lens) triple
    computed once for all columns.  Returns None when the column must
    take the copy path."""
    col = result.column(field_id)
    if col["kind"] != "span":
        return None
    B = result.lines_read
    overrides = result._overrides.get(field_id, {})
    ov_rows: List[int] = []
    ov_vals: List[bytes] = []
    for r, v in overrides.items():
        if v is None:
            continue
        if not isinstance(v, str):
            return None
        ov_rows.append(r)
        ov_vals.append(v.encode("utf-8"))

    if base is not None:
        valid, starts, lens = base
    else:
        valid = (
            np.asarray(result.valid[:B]).astype(bool)
            & np.asarray(col["ok"][:B]).astype(bool)
            & ~np.asarray(col["null"][:B]).astype(bool)
        )
        starts = np.asarray(col["starts"][:B], dtype=np.int32)
        lens = np.where(
            valid, np.asarray(col["ends"][:B]) - starts, -1
        ).astype(np.int32)
    arr_valid = valid if not overrides else valid.copy()
    for r, v in overrides.items():
        arr_valid[r] = v is not None
    if ov_rows:
        lens = lens.copy()
        lens[np.asarray(ov_rows)] = -1  # patched from the side buffer

    fix = col.get("fix")
    amp = col.get("amp")
    fix_m = (
        np.asarray(fix[:B], dtype=bool) & valid
        if fix is not None else None
    )
    if fix_m is not None and not fix_m.any():
        fix_m = None
    amp_m = None
    if amp is not None:
        cand = np.asarray(amp[:B], dtype=bool) & valid & (lens > 0)
        if cand.any():
            first = buf[np.nonzero(cand)[0], starts[cand]]
            cand[np.nonzero(cand)[0]] = first == np.uint8(ord("?"))
            amp_m = cand if cand.any() else None
    if ov_rows and (fix_m is not None or amp_m is not None):
        sel = np.zeros(B, dtype=bool)
        sel[np.asarray(ov_rows)] = True
        if fix_m is not None:
            fix_m &= ~sel
        if amp_m is not None:
            amp_m &= ~sel
    def sp_tuple(mask):
        """Per-special-row data for the fused native assembler, in
        special-row order: (rows, span lens, fix flags, amp flags)."""
        rows = np.nonzero(mask)[0]
        return (
            rows,
            lens[rows].astype(np.int64),
            (fix_m[rows].astype(np.uint8) if fix_m is not None
             else np.zeros(rows.size, dtype=np.uint8)),
            (amp_m[rows].astype(np.uint8) if amp_m is not None
             else np.zeros(rows.size, dtype=np.uint8)),
        )

    if fix_m is not None or amp_m is not None:
        special = (
            fix_m if amp_m is None
            else (amp_m if fix_m is None else fix_m | amp_m)
        )
        lens_main = lens.copy()
        lens_main[special] = -1  # patched from the side buffer
        # Precomputed (line-invariant, like the masks above) special-row
        # data.  sp_dev is the reduced set for DEVICE-emitted views:
        # amp-only rows of <= 12 bytes are fully inline and the device
        # already rendered their '&', so only fix rows and long amp rows
        # need the host side buffer.
        sp = sp_tuple(special)
        if amp_m is not None:
            amp_only = amp_m if fix_m is None else (amp_m & ~fix_m)
            reduced = special & ~(amp_only & (lens <= 12))
            sp_dev = sp_tuple(reduced) if reduced.any() else None
        else:
            sp_dev = sp
    else:
        special = None
        lens_main = lens
        sp = None
        sp_dev = None
    state = {
        "col": col, "valid": valid, "arr_valid": arr_valid, "lens": lens,
        "special": special, "fix_m": fix_m, "amp_m": amp_m,
        "ov_rows": ov_rows, "ov_vals": ov_vals, "sp": sp, "sp_dev": sp_dev,
        # Cached Arrow null bitmap (None = no nulls): packbits per call
        # was ~7 x 20 us per table on the 1-core host.
        "null_bitmap": _null_bitmap(arr_valid),
    }
    return starts, lens_main, state


def _assemble_view_array(result: "BatchResult", buf, starts, views, state,
                         dev_views: bool = False, threads: int = 0):
    """Side-buffer handling + pa.Array assembly for one view column.
    ``dev_views`` marks views interleaved from device-emitted rows (short
    amp-only rows are already rendered inline there).  ``threads`` caps
    the native side-buffer fan-out (pooled per-column callers pass 1 so
    the column-level parallelism supplies the concurrency)."""
    import pyarrow as pa

    from ..native import (
        assemble_special, copy_spans, patch_views, scatter_spans,
    )

    col = state["col"]
    arr_valid = state["arr_valid"]
    lens = state["lens"]
    special = state["special"]
    fix_m = state["fix_m"]
    amp_m = state["amp_m"]
    ov_rows, ov_vals = state["ov_rows"], state["ov_vals"]
    # Device-emitted views already carry the '&' of short (inline)
    # amp-only rows — only the reduced special set needs the side buffer.
    sp = state["sp_dev"] if dev_views else state["sp"]
    B = result.lines_read
    L = buf.shape[1]
    views = np.ascontiguousarray(views.reshape(B, 16))
    variadic = [pa.py_buffer(buf.reshape(-1))]
    fused = None
    if special is not None and sp is not None:
        # Fused native path: ONE scan+write pair builds the side buffer
        # and patches the views straight from the batch buffer (the
        # unfused flow below spent ~1.2 ms/column in numpy indexing and
        # per-call dispatch for ~0.6 MB of actual byte work).
        sp_rows, sp_lens, sp_fix, sp_amp = sp
        mode_str = col.get("fix_mode")
        fused = assemble_special(
            buf, starts, sp_rows, sp_lens, sp_fix, sp_amp,
            0 if mode_str in ("path", "userinfo") else 1,
            _IS_ENC, views, len(variadic), threads=threads,
        )
    if fused == "overflow":
        # >2 GiB side buffer would wrap the int32 view offsets: the
        # column takes the copy path (which guards offsets itself).
        return None
    # dev route with an empty reduced set: every special row was rendered
    # inline on device; nothing to patch.
    handled_inline = special is not None and sp is None and dev_views
    if fused is not None:
        from .batch import _fix_uri_part

        side, side_off, py_flags = fused
        variadic.append(pa.py_buffer(side))
        if py_flags.any():
            # Exact Python UTF-8 semantics for the flagged rows (non-ASCII
            # bytes / non-ASCII decode results): amp-normalize, repair,
            # patch from an extra side buffer.  Twin of the py-row flow in
            # _repair_fix_segments — change both together (the fuzz suite
            # locks them against the oracle).
            sp_rows, sp_lens, sp_fix, sp_amp = sp
            py_sel = np.nonzero(py_flags)[0]
            py_vals = []
            for k in py_sel.tolist():
                r = int(sp_rows[k])
                raw = bytes(buf[r, starts[r]: starts[r] + int(sp_lens[k])])
                if sp_amp[k]:
                    raw = b"&" + raw[1:]
                py_vals.append(
                    _fix_uri_part(
                        raw.decode("utf-8", "replace"), col["fix_mode"]
                    ).encode("utf-8")
                )
            py_flat = np.frombuffer(b"".join(py_vals), dtype=np.uint8)
            py_off = np.zeros(len(py_vals) + 1, dtype=np.int64)
            np.cumsum([len(v) for v in py_vals], out=py_off[1:])
            patch_views(views, sp_rows[py_sel], py_flat, py_off,
                        len(variadic))
            variadic.append(pa.py_buffer(py_flat))
    elif special is not None and not handled_inline:
        # Single-allocation side-buffer assembly: repair segments gather
        # straight from the batch buffer, then clean-special and repaired
        # rows SCATTER into one final buffer (the former flow copied all
        # special bytes up to three times: sub -> f_seg -> concat+recopy).
        rows = np.nonzero(special)[0]
        sub_lens = lens[rows].astype(np.int64)
        src_off = rows.astype(np.int64) * L + starts[rows]
        fix_sub = (
            np.nonzero(fix_m[rows])[0] if fix_m is not None
            else np.empty(0, dtype=np.int64)
        )
        rep_flat = None
        if fix_sub.size:
            f_lens = sub_lens[fix_sub]
            f_off = np.zeros(fix_sub.size + 1, dtype=np.int64)
            np.cumsum(f_lens, out=f_off[1:])
            f_seg = copy_spans(buf.reshape(-1), src_off[fix_sub], f_off)
            if amp_m is not None:
                # ?->& applies before repair sees the bytes (repair rows
                # can carry the query-normalization flag too).
                amp_fix = amp_m[rows][fix_sub]
                if amp_fix.any():
                    f_seg[f_off[:-1][amp_fix]] = np.uint8(ord("&"))
            rep_flat, rep_lens = _repair_fix_segments(
                f_seg, f_off, col["fix_mode"]
            )
            rep_off = np.zeros(fix_sub.size + 1, dtype=np.int64)
            np.cumsum(rep_lens, out=rep_off[1:])
        new_lens = sub_lens
        if rep_flat is not None:
            new_lens = sub_lens.copy()
            new_lens[fix_sub] = rep_lens
        sub_off = np.zeros(rows.size + 1, dtype=np.int64)
        np.cumsum(new_lens, out=sub_off[1:])
        if int(sub_off[-1]) >= 2**31:
            return None  # int32 view offsets would wrap: copy path
        sub = np.empty(int(sub_off[-1]), dtype=np.uint8)
        if fix_sub.size:
            nonfix = np.ones(rows.size, dtype=bool)
            nonfix[fix_sub] = False
            scatter_spans(buf.reshape(-1), src_off[nonfix],
                          sub_lens[nonfix], sub, sub_off[:-1][nonfix])
            scatter_spans(rep_flat, rep_off[:-1], rep_lens,
                          sub, sub_off[:-1][fix_sub])
            if amp_m is not None:
                amp_sub = amp_m[rows] & nonfix
                if amp_sub.any():
                    sub[sub_off[:-1][amp_sub]] = np.uint8(ord("&"))
        else:
            scatter_spans(buf.reshape(-1), src_off, sub_lens,
                          sub, sub_off[:-1])
            if amp_m is not None:
                amp_sub = amp_m[rows]
                if amp_sub.any():
                    sub[sub_off[:-1][amp_sub]] = np.uint8(ord("&"))
        patch_views(views, rows, sub, sub_off, len(variadic))
        variadic.append(pa.py_buffer(sub))
    if ov_rows:
        ov_flat = np.frombuffer(b"".join(ov_vals), dtype=np.uint8)
        ov_off = np.zeros(len(ov_rows) + 1, dtype=np.int64)
        np.cumsum([len(v) for v in ov_vals], out=ov_off[1:])
        patch_views(views, np.asarray(ov_rows), ov_flat, ov_off,
                    len(variadic))
        variadic.append(pa.py_buffer(ov_flat))

    nb = state["null_bitmap"]
    arr = pa.Array.from_buffers(
        pa.string_view(), B,
        [None if nb is None else pa.py_buffer(nb), pa.py_buffer(views)]
        + variadic,
    )
    if not result.ascii_only:
        try:
            arr.validate(full=True)
        except pa.ArrowInvalid:
            return None
    return arr


def _spans_to_view_array(result: "BatchResult", field_id: str):
    """Zero-copy span column -> pa.StringViewArray.

    Arrow's BinaryView layout stores (length, prefix, buffer, offset) per
    element, so clean rows reference the batch's [B, L] byte buffer
    IN PLACE — no gather, no value copy; only the 16-byte view structs
    are built (native lp_build_views).  Rows the buffer bytes cannot
    represent — URI-repair ``fix`` rows, ``amp`` (?->&) rows,
    host-override rows — land in a compact side buffer (repaired via
    _repair_fix_segments) that the views reference as further data
    buffers.  Returns None when the column needs the copy path (non-str
    overrides, >2^31 buffer, or non-UTF-8 values)."""
    import pyarrow as pa

    from ..native import build_views

    B = result.lines_read
    if B == 0:
        return pa.array([], type=pa.string_view())
    buf = np.ascontiguousarray(result.buf[:B])
    if buf.size >= 2**31:
        return None
    pre = _view_column_inputs(result, field_id, buf)
    if pre is None:
        return None
    starts, lens_main, state = pre
    views = build_views(buf, starts[None, :], lens_main[None, :])[0]
    return _assemble_view_array(result, buf, starts, views, state)


def _span_view_arrays(result: "BatchResult", field_ids,
                      pool=None) -> Dict[str, Any]:
    """Batched view materialization: ONE native lp_build_views call
    covers every eligible span column (the per-call thread-pool spawn
    dominated per-column builds), then the per-column side-buffer
    assembly fans out over ``pool`` (tpu/hostpool.py).  Ineligible
    columns are absent."""
    import pyarrow as pa

    from ..native import build_views

    out: Dict[str, Any] = {}
    if not hasattr(pa, "string_view"):
        return out
    B = result.lines_read
    if B == 0:
        return out
    buf = np.ascontiguousarray(result.buf[:B])
    if buf.size >= 2**31:
        return out
    span_fids = [
        fid for fid in field_ids
        if result.column(fid)["kind"] == "span"
    ]
    if not span_fids:
        return out
    # Batched base prep: ONE stacked pass computes valid/starts/lens for
    # every span column (per-column [B] numpy chains added up).  The
    # result is line-invariant per batch, so it is memoized on the
    # BatchResult like the other per-batch decode caches (ascii check,
    # lazy wildcards) — the delivered views themselves are rebuilt on
    # every call.
    pre_cache = result.__dict__.setdefault("_view_pre", {})
    missing = [fid for fid in span_fids if fid not in pre_cache]
    if missing:
        # Batched base prep: ONE stacked pass computes valid/starts/lens
        # for every span column; the per-column pre (incl. special-row
        # masks) is line-invariant per batch and memoized on the
        # BatchResult like the other per-batch decode caches (ascii
        # check, lazy wildcards) — the delivered views and side buffers
        # themselves are rebuilt on every call.
        cols = [result.column(fid) for fid in missing]
        line_valid = np.asarray(result.valid[:B]).astype(bool)
        ok_k = np.stack([np.asarray(c["ok"][:B], dtype=bool) for c in cols])
        null_k = np.stack(
            [np.asarray(c["null"][:B], dtype=bool) for c in cols]
        )
        starts_k = np.stack(
            [np.asarray(c["starts"][:B], dtype=np.int32) for c in cols]
        )
        ends_k = np.stack(
            [np.asarray(c["ends"][:B], dtype=np.int32) for c in cols]
        )
        valid_k = ok_k & ~null_k & line_valid[None, :]
        lens_k = np.where(valid_k, ends_k - starts_k, -1).astype(np.int32)
        for k, fid in enumerate(missing):
            pre_cache[fid] = _view_column_inputs(
                result, fid, buf, base=(valid_k[k], starts_k[k], lens_k[k])
            )
    for fid in span_fids:
        if pre_cache[fid] is None:
            out[fid] = _VIEW_FAILED  # copy path; don't rebuild per column
    pres = [
        (fid, pre_cache[fid]) for fid in span_fids
        if pre_cache[fid] is not None
    ]
    if not pres:
        return out
    # Columns with device-emitted view rows interleave straight from the
    # packed fetch (native streaming pass, no [B, L] buffer traffic); the
    # rest build on host from the stacked starts/lens.  The batched
    # native passes take the pool's full thread budget; the per-column
    # assemblies then fan out over the pool with single-threaded native
    # calls (hostpool contract: the two layers never oversubscribe).
    from .hostpool import MIN_POOLED_ROWS, VIEW_POOL_MIN_WORKERS

    use_pool = (
        pool is not None
        and pool.workers >= VIEW_POOL_MIN_WORKERS
        and B >= MIN_POOLED_ROWS
    )
    n_threads = pool.native_threads if pool is not None else 0
    task_threads = 1 if use_pool else n_threads
    dev = [p for p in pres if p[0] in result.device_views]
    host = [p for p in pres if p[0] not in result.device_views]
    tasks = []
    task_fids = []
    if dev:
        from ..native import views_interleave

        field_rows = np.asarray(
            [result.device_views[fid] for fid, _ in dev], dtype=np.int64
        )
        dev_views = views_interleave(result.packed, field_rows, B,
                                     buf.shape[1], threads=n_threads)
        if dev_views is None:
            host = pres  # no native library: host-built views for all
        else:
            if result.dirty_view_rows.size:
                dev_views[:, result.dirty_view_rows, :] = 0
            for k, (fid, (st, _lm, state)) in enumerate(dev):
                tasks.append(
                    lambda st=st, v=dev_views[k], state=state:
                    _assemble_view_array(result, buf, st, v, state,
                                         dev_views=True,
                                         threads=task_threads)
                )
                task_fids.append(fid)
    if host:
        starts = np.stack([p[1][0] for p in host])
        lens = np.stack([p[1][1] for p in host])
        views = build_views(buf, starts, lens, threads=n_threads)
        for k, (fid, (st, _lm, state)) in enumerate(host):
            tasks.append(
                lambda st=st, v=views[k], state=state:
                _assemble_view_array(result, buf, st, v, state,
                                     threads=task_threads)
            )
            task_fids.append(fid)
    arrs = pool.run_all(tasks) if use_pool else [t() for t in tasks]
    for fid, arr in zip(task_fids, arrs):
        out[fid] = arr if arr is not None else _VIEW_FAILED
    return out


def _column_to_arrow(
    result: "BatchResult", field_id: str, flat: Optional[Any] = None,
    strings: str = "view", prebuilt: Optional[Any] = None,
):
    import pyarrow as pa

    col = result.column(field_id)
    kind = col["kind"]
    overrides = result._overrides.get(field_id, {})
    B = result.lines_read

    if kind == "span" and not field_id.endswith(".*") and strings == "view":
        if not hasattr(pa, "string_view"):
            # Older pyarrow without the BinaryView type (added in 14,
            # buildable from buffers in 16): classic StringArrays.
            return _column_to_arrow(result, field_id, flat, strings="copy")
        if prebuilt is None:
            # Standalone call (no batched prefetch attempted).
            prebuilt = _spans_to_view_array(result, field_id)
        elif prebuilt is _VIEW_FAILED:
            # The batched pass already tried and failed this column
            # (non-str override / non-UTF-8) — don't rebuild it just to
            # fail the same way.
            prebuilt = None
        if prebuilt is not None:
            return prebuilt
        # Copy-path fallback (non-str overrides / oversized buffer /
        # non-UTF-8): cast string results to string_view so the column
        # type stays stable across batches.
        arr = _column_to_arrow(result, field_id, flat, strings="copy")
        if pa.types.is_string(arr.type):
            arr = arr.cast(pa.string_view())
        return arr

    if kind == "numeric" and not any(
        isinstance(v, (str, dict)) for v in overrides.values()
    ):
        values = np.asarray(col["values"], dtype=np.int64).copy()
        mask = ~(np.asarray(result.valid) & np.asarray(col["ok"]))
        null = np.asarray(col["null"])
        # Per-line CLF-zero semantics: the format that won the line decides
        # whether '-' means 0 (ConvertCLFIntoNumber) or null.
        null_zero = np.asarray(col["null_zero"])
        values[null & null_zero] = 0
        mask = mask | (null & ~null_zero)
        for row, v in overrides.items():
            if v is None or not -2**63 <= v < 2**63:
                # Beyond-int64 oracle values (>18-digit counters) deliver
                # NULL in the typed column — exactly the reference's
                # Long.parseLong null on its Long-typed setters;
                # to_pylist still carries the full python int.
                mask[row] = True
            else:
                values[row] = v
                mask[row] = False
        # Zero-copy wrap: pa.array(values, mask=...) re-copies the value
        # buffer and rebuilds the bitmap at C level but still costs ~2x
        # this from_buffers path per column on the 1-core host.
        nb = _null_bitmap(~mask[:B])
        return pa.Array.from_buffers(
            pa.int64(), B,
            [None if nb is None else pa.py_buffer(nb),
             pa.py_buffer(np.ascontiguousarray(values[:B]))],
        )

    # Device span columns with no host overrides: build the StringArray
    # straight from (offsets, gathered bytes) with numpy — no per-row
    # Python; URI-repair (`fix`) rows are spliced in individually.  Falls
    # through to the slow path for override rows (host fallback),
    # wildcard maps, and non-UTF-8 data.
    if kind == "span" and not field_id.endswith(".*") and not overrides:
        arr = _spans_to_string_array(result, field_id, flat)
        if arr is not None:
            return arr

    if field_id.endswith(".*"):
        # Wildcard map columns: the flat CSR buffers build the MapArray
        # directly when possible (no per-row dict materialization at all);
        # the dict path handles the exact-semantics leftovers.
        from .batch import _LazyWildcard

        if isinstance(overrides, _LazyWildcard):
            arr = overrides.to_arrow_map(B)
            if arr is not None:
                return arr
        return pa.array(
            [
                None if v is None else list(v.items())
                for v in result.to_pylist(field_id)
            ],
            type=pa.map_(pa.string(), pa.string()),
        )

    # Host-delivered obj columns (GeoIP range-join results, muid decodes):
    # the values already sit in an object ndarray of Python str/int/float —
    # mask the dead rows vectorized and let pyarrow's C-level inference
    # build the array; only mixed-type columns fall back to the per-row
    # stringify path below.
    if kind == "obj":
        dead = ~(
            np.asarray(result.valid[:B], dtype=bool)
            & np.asarray(col["ok"][:B], dtype=bool)
        )
        # Low-cardinality device-joined strings (GeoIP vocab columns)
        # carry their vocab codes: dictionary.take(codes) builds the
        # string column entirely in C (the object-array inference below
        # was ~1 ms/column at 16k rows).
        codes = col.get("dict_codes")
        dvals = col.get("dict_values")
        mixed = col.get("mixed_fill", False)
        if codes is not None and dvals is not None and not mixed \
                and not overrides:
            c = codes[:B].copy()
            c[dead] = -1
            miss = c < 0
            ind = pa.array(
                np.clip(c, 0, None).astype(np.int32),
                mask=miss,
            )
            return _pa_vocab(dvals).take(ind)
        # Numeric geo columns (asn.number, lat/lon confidences) carry
        # their raw typed values + miss mask — same column types as the
        # inference path (int64/double), no per-element work.
        if col.get("typed_kind") and not mixed and not overrides:
            tv = np.asarray(col["typed_values"][:B])
            return pa.array(tv, mask=dead | col["typed_miss"][:B])
        vals = np.asarray(col["values"], dtype=object)[:B]
        if dead.any() or overrides:
            vals = vals.copy()
            vals[dead] = None
            for row, v in overrides.items():
                vals[row] = v
        try:
            arr = pa.array(vals, from_pandas=True)
            # Keep the batch-to-batch schema stable: an all-null batch
            # must stay a string column (as the per-row path types it),
            # not pa.null() — pa.concat_tables across batches depends on
            # it.  Booleans likewise stringify on the per-row path.
            if not (
                pa.types.is_null(arr.type) or pa.types.is_boolean(arr.type)
            ):
                return arr
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            pass  # mixed types: per-row inference below

    # Host-delivered / span columns: type from the materialized values
    # (host-path numerics — e.g. dissector-produced numbers like GeoIP
    # asn.number — must come out int64/float64, not stringified).
    values_py = result.to_pylist(field_id)
    non_null = [v for v in values_py if v is not None]
    if non_null and all(isinstance(v, int) and not isinstance(v, bool) for v in non_null):
        return pa.array(values_py, type=pa.int64())
    if non_null and all(
        isinstance(v, (int, float)) and not isinstance(v, bool) for v in non_null
    ):
        return pa.array(
            [None if v is None else float(v) for v in values_py],
            type=pa.float64(),
        )
    return pa.array(
        [None if v is None else str(v) for v in values_py], type=pa.string()
    )


def batch_to_arrow(
    result: "BatchResult", include_validity: bool = True,
    strings: str = "view", pool=None,
):
    """BatchResult -> pyarrow.Table (one column per requested field).

    ``strings="view"`` (default) delivers span columns as Arrow
    string_view arrays referencing the batch buffer zero-copy — the table
    shares the batch's memory (kept alive by the Arrow buffers).
    ``strings="copy"`` builds classic contiguous StringArrays instead
    (self-contained value buffers; the pre-round-4 behavior).

    ``pool`` (default: the result's attached assembly pool) fans the
    per-column assembly across worker threads: span and numeric columns
    are independent numpy/pyarrow/native work that releases the GIL, so
    they parallelize; wildcard/obj/fallback columns share mutable
    per-result caches and stay on the caller thread.  A 1-wide pool is
    exactly the serial path (thread-count parity is a tested contract)."""
    from ..observability import pipeline_stage

    with pipeline_stage("assembly", items=result.lines_read):
        return _batch_to_arrow(
            result, include_validity=include_validity, strings=strings,
            pool=pool,
        )


def _batch_to_arrow(
    result: "BatchResult", include_validity: bool = True,
    strings: str = "view", pool=None,
):
    import pyarrow as pa

    from .hostpool import MIN_POOLED_ROWS, VIEW_POOL_MIN_WORKERS

    if pool is None:
        pool = getattr(result, "assembly_pool", None)
    # Mode-dependent engage rule (measured, see hostpool.py): copy-mode
    # columns are one big GIL-released native gather each — they pool
    # from 2 workers; view-mode columns are GIL-holding assembly and
    # need more workers to win.
    pooled = (
        pool is not None
        and result.lines_read >= MIN_POOLED_ROWS
        and pool.workers >= (
            VIEW_POOL_MIN_WORKERS if strings == "view" else 2
        )
    )
    result.ascii_only  # compute the lazy batch-wide check once, serially
    span_fids = [f for f in result.field_ids() if not f.endswith(".*")]
    if strings == "view":
        flats: Dict[str, Any] = {}
        prebuilt = _span_view_arrays(result, span_fids, pool=pool)
    else:
        prebuilt = {}
        if pooled:
            # Per-column gathers fan out over the pool below: each column
            # gathers into its OWN buffer (native threads=1; concurrency
            # comes from the column fan-out), so the per-column re-copy
            # the shared multi-gather buffer forced in
            # _spans_to_string_array disappears.
            flats = {}
        else:
            flats = result.span_bytes_many(span_fids, include_fix=True)

    def build_column(field_id):
        flat = flats.get(field_id)
        if (
            strings == "copy" and pooled and flat is None
            and not field_id.endswith(".*")
            and result.column(field_id)["kind"] == "span"
        ):
            flat = result.span_bytes(field_id, include_fix=True, threads=1)
        return _column_to_arrow(
            result, field_id, flat, strings=strings,
            prebuilt=prebuilt.get(field_id),
        )

    fids = result.field_ids()
    # Columns safe to assemble concurrently: span/numeric device columns
    # (own arrays, read-only shared state).  Wildcard maps (_LazyWildcard
    # materialization), obj columns (shared vocab cache) and anything
    # else run serially on the caller thread.
    parallel_ok = {
        fid for fid in fids
        if not fid.endswith(".*")
        and result.column(fid)["kind"] in ("span", "numeric")
    }
    by_fid: Dict[str, Any] = {}
    if pooled and len(parallel_ok) > 1:
        par = [fid for fid in fids if fid in parallel_ok]
        arrs = pool.run_all(
            [lambda f=fid: build_column(f) for fid in par]
        )
        by_fid.update(zip(par, arrs))
    for field_id in fids:
        if field_id not in by_fid:
            by_fid[field_id] = build_column(field_id)
    arrays = [by_fid[fid] for fid in fids]
    names = list(fids)
    if include_validity:
        arrays.append(pa.array(np.asarray(result.valid, dtype=bool)))
        names.append("__valid__")
    return pa.table(dict(zip(names, arrays)))


def table_to_ipc_bytes(table) -> bytes:
    """Arrow IPC stream serialization (the cross-process/sidecar format)."""
    import pyarrow as pa

    from ..observability import metrics, pipeline_stage

    with pipeline_stage("ipc", items=table.num_rows):
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        out = sink.getvalue()
    metrics().increment("ipc_bytes_out_total", len(out))
    return out


def table_from_ipc_bytes(data: bytes):
    import pyarrow as pa

    with pa.ipc.open_stream(io.BytesIO(data)) as reader:
        return reader.read_all()


def parse_to_ipc(parser, lines) -> bytes:
    """One-call sidecar surface: lines in, Arrow IPC stream bytes out.

    ``lines`` is a sequence of loglines, or a newline-delimited bytes
    blob (routed through the list-free ``parse_blob`` ingest).

    Serialization uses the contiguous copy mode: IPC does not dedupe
    shared buffers, so a string_view table would ship one copy of the
    whole batch buffer PER span column over the wire.  Because no
    string_view column is ever delivered, the device view-row emission
    is skipped too (demand-driven: the view rows would be pure kernel
    and D2H cost on this path)."""
    if isinstance(lines, (bytes, bytearray, memoryview)):
        result = parser.parse_blob(lines, emit_views=False)
    else:
        result = parser.parse_batch(lines, emit_views=False)
    return table_to_ipc_bytes(batch_to_arrow(result, strings="copy"))
