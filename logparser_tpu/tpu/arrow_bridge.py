"""Arrow materialization of batch parse results + IPC interop.

SURVEY §7 step 5: "host materializes Arrow arrays ... Java/any-host interop
over Arrow IPC; sidecar service mode".  The reference has no columnar output
(records go through per-line reflection setters); Arrow is the TPU-native
equivalent of that record-delivery surface: span columns gather straight from
the [B, L] byte buffer into a StringArray, numeric columns become int64 with
a null bitmap, wildcard columns become map<string,string>.

Zero-copy note: device span columns build the StringArray from numpy-gathered
(offsets, bytes) buffers wrapped zero-copy — no per-row Python.  Only the
fallback path (host-override rows, wildcard maps, non-UTF-8 data) goes
through ``to_pylist``'s per-row decode.  Numeric columns are pure numpy.
"""
from __future__ import annotations

import io
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Sequence

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from .batch import BatchResult



def _spans_to_string_array(
    result: "BatchResult", field_id: str, flat: Optional[Any] = None
) -> Optional[Any]:
    """Vectorized span -> pa.StringArray built on BatchResult.span_bytes
    (the single flat-gather implementation: validity mask, native gather,
    ?&-normalization).  ``flat`` carries a prefetched (data, offsets,
    valid) triple from the batch-wide multi-column gather.  Returns None
    when the column needs the per-row path or the gathered bytes are not
    valid UTF-8."""
    import pyarrow as pa

    B = result.lines_read
    if B == 0:
        return pa.array([], type=pa.string())
    if flat is None:
        flat = result.span_bytes(field_id)
    if flat is None:
        return None
    data, offsets64, valid = flat
    data, offsets64 = _splice_fix_rows(result, field_id, data, offsets64, valid)
    if int(offsets64[-1]) > np.iinfo(np.int32).max:
        # int32 StringArray offsets would wrap; don't rely on validate()
        # catching it after the full gather — take the fallback path now.
        return None
    data = np.ascontiguousarray(data)
    if data.base is not None:
        # A view into the batch-wide multi-column gather buffer: wrapping
        # it zero-copy into the Arrow buffer would pin EVERY span
        # column's bytes for as long as this one column lives.  Copy the
        # column's own bytes (one memcpy, small next to the gather).
        data = data.copy()
    offsets = offsets64.astype(np.int32)
    null_bitmap = np.packbits(valid, bitorder="little")
    # pa.py_buffer wraps the numpy arrays zero-copy (buffer protocol);
    # .tobytes() here would duplicate the data buffer per batch.
    arr = pa.StringArray.from_buffers(
        B,
        pa.py_buffer(offsets),
        pa.py_buffer(data),
        pa.py_buffer(null_bitmap),
    )
    if result.ascii_only:
        # Every source byte is < 0x80, so every gathered span is valid
        # UTF-8 by construction — the per-column validate pass (a third
        # of the column build cost) is provably redundant.
        return arr
    try:
        arr.validate(full=True)  # UTF-8 check happens here
    except pa.ArrowInvalid:
        return None
    return arr


_HEX_VAL = np.full(256, -1, dtype=np.int16)
for _c in b"0123456789":
    _HEX_VAL[_c] = _c - ord("0")
for _c in b"abcdef":
    _HEX_VAL[_c] = _c - ord("a") + 10
for _c in b"ABCDEF":
    _HEX_VAL[_c] = _c - ord("A") + 10
_IS_HEX = _HEX_VAL >= 0
# Printable URI encode-set bytes (postproc.split_uri_fast's `enc`): the
# host %-escapes these before any other repair stage.  Built from the
# host dissector's own constant so device and host cannot drift.
from ..dissectors.uri import ENCODE_PRINTABLE as _ENCODE_PRINTABLE

_IS_ENC = np.zeros(256, dtype=bool)
for _c in _ENCODE_PRINTABLE:
    _IS_ENC[_c] = True
_HEX_UPPER = np.frombuffer(b"0123456789ABCDEF", dtype=np.uint8)


def _splice_fix_rows(result: "BatchResult", field_id: str, data, offsets, valid):
    """Patch URI-repair (`fix`) rows into gathered flat span bytes.

    The flat gather copies repair rows RAW; the repair semantics
    (%-bad-escape rewrite + path %XX decode, HttpUriDissector.java:166-167
    / java.net.URI decode) run here VECTORIZED over the concatenated
    fix-row bytes: rows whose escapes are all well-formed ``%XX`` decode
    with numpy scatter/gather; only rows with bad escapes, non-ASCII raw
    bytes, or non-ASCII decode results (UTF-8 replacement semantics) take
    the per-row ``_fix_uri_part`` path.  Spliced python-row values
    re-encode through UTF-8, so they are valid by construction."""
    from .batch import _fix_uri_part

    col = result.column(field_id)
    fix = col.get("fix")
    B = result.lines_read
    if fix is None:
        return data, offsets
    rows = np.nonzero(np.asarray(fix[:B], dtype=bool) & valid)[0]
    if rows.size == 0:
        return data, offsets
    mode = col["fix_mode"]
    lens = np.diff(offsets)
    seg_lens = lens[rows]
    n_rows = rows.size
    seg_off = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(seg_lens, out=seg_off[1:])
    total = int(seg_off[-1])
    idx = np.repeat(offsets[rows] - seg_off[:-1], seg_lens) + np.arange(
        total, dtype=np.int64
    )
    seg = data[idx]
    row_id = np.repeat(np.arange(n_rows, dtype=np.int64), seg_lens)

    # Classify every '%' as a well-formed %XX escape or a bad escape
    # (reference _BAD_ESCAPE_PATTERN: % not followed by two hex digits,
    # including at end-of-value).
    nxt1 = np.zeros(total, dtype=np.uint8)
    nxt2 = np.zeros(total, dtype=np.uint8)
    same1 = np.zeros(total, dtype=bool)
    same2 = np.zeros(total, dtype=bool)
    if total > 1:
        nxt1[:-1] = seg[1:]
        same1[:-1] = row_id[1:] == row_id[:-1]
    if total > 2:
        nxt2[:-2] = seg[2:]
        same2[:-2] = row_id[2:] == row_id[:-2]
    pct = seg == ord("%")
    good = pct & same1 & same2 & _IS_HEX[nxt1] & _IS_HEX[nxt2]
    bad = pct & ~good

    def row_any(mask):
        out = np.zeros(n_rows, dtype=bool)
        if mask.any():
            out[np.unique(row_id[mask])] = True
        return out

    # Rows needing the exact per-row semantics: raw non-ASCII bytes (the
    # UTF-8 decode-replace round trip can rewrite invalid sequences) and,
    # in path mode, non-ASCII decode results (multi-escape runs decode as
    # one UTF-8 unit).  Everything else vectorizes:
    # - The reference's TWICE-applied sequential %25 rewrite
    #   (HttpUriDissector.java:166-167) is equivalent to ONE simultaneous
    #   "insert 25 after every originally-bad %": pass-1 consumption can
    #   only defer a bad escape's rewrite to pass 2 (never prevent it),
    #   a rewritten escape is %25-good and never rematched, and no
    #   insertion can land between a good % and its two hex digits.
    # - In path mode, repairing a bad escape then decoding it
    #   (%zz -> %25zz -> %zz) is the identity, so bad escapes simply stay
    #   literal and only good %XX escapes substitute their byte.
    enc = _IS_ENC[seg]
    py_rows = row_any(seg >= 0x80)
    if mode in ("path", "userinfo"):
        # Decoding modes: good %XX escapes substitute their byte; bad
        # escapes stay literal (the %25-repair and the later decode
        # cancel); encode-set bytes are an encode->decode identity.
        dec = ((_HEX_VAL[nxt1] << 4) | np.maximum(_HEX_VAL[nxt2], 0)).astype(
            np.int16
        )
        py_rows |= row_any(good & (dec >= 0x80))
        vec_changed = row_any(good) & ~py_rows
    else:
        # Escaping modes (query): well-formed escapes are untouched; bad
        # escapes gain a '25' insertion and encode-set bytes expand to
        # their uppercase %XX triple.
        vec_changed = row_any(bad | enc) & ~py_rows

    py_idx = np.nonzero(py_rows)[0]
    changed_local = np.nonzero(vec_changed | py_rows)[0]
    if changed_local.size == 0:
        return data, offsets

    pieces = [data]
    src_base = offsets[:-1].astype(np.int64, copy=True)
    new_lens = lens.copy()
    if vec_changed.any():
        in_vec = vec_changed[row_id]
        if mode in ("path", "userinfo"):
            # Drop the two hex tail bytes of each good escape, replace
            # the '%' with the decoded byte.
            g = good & in_vec
            tail = np.zeros(total, dtype=bool)
            tail[1:] |= g[:-1]
            tail[2:] |= g[:-2]
            keep = in_vec & ~tail
            new_seg = np.where(g, dec.astype(np.uint8), seg)[keep]
            row_counts = np.bincount(row_id[keep], minlength=n_rows)
        else:
            # Simultaneous bad-escape rewrite + encode: a bad '%' expands
            # to '%25', an encode-set byte to its uppercase '%XX' triple.
            sel = in_vec
            sv = seg[sel]
            bv = (bad & in_vec)[sel]
            ev = (enc & in_vec)[sel]
            rid_v = row_id[sel]
            counts = np.where(bv | ev, 3, 1).astype(np.int64)
            out_pos = np.zeros(sv.size + 1, dtype=np.int64)
            np.cumsum(counts, out=out_pos[1:])
            new_seg = np.repeat(sv, counts)
            ins = out_pos[:-1][bv]
            new_seg[ins + 1] = ord("2")
            new_seg[ins + 2] = ord("5")
            ein = out_pos[:-1][ev]
            new_seg[ein] = ord("%")
            new_seg[ein + 1] = _HEX_UPPER[sv[ev] >> 4]
            new_seg[ein + 2] = _HEX_UPPER[sv[ev] & 0x0F]
            row_counts = np.bincount(
                rid_v, weights=counts, minlength=n_rows
            ).astype(np.int64)
        vloc = np.nonzero(vec_changed)[0]
        voff = np.zeros(vloc.size + 1, dtype=np.int64)
        np.cumsum(row_counts[vloc], out=voff[1:])
        src_base[rows[vloc]] = len(data) + voff[:-1]
        new_lens[rows[vloc]] = row_counts[vloc]
        pieces.append(new_seg)
    if py_idx.size:
        py_bytes = [
            _fix_uri_part(
                bytes(seg[seg_off[j] : seg_off[j + 1]]).decode("utf-8", "replace"),
                mode,
            ).encode("utf-8")
            for j in py_idx.tolist()
        ]
        py_buf = np.frombuffer(b"".join(py_bytes), dtype=np.uint8)
        base = sum(len(p) for p in pieces)
        off = 0
        for j, v in zip(py_idx.tolist(), py_bytes):
            src_base[rows[j]] = base + off
            new_lens[rows[j]] = len(v)
            off += len(v)
        pieces.append(py_buf)

    from ..native import copy_spans

    combined = np.concatenate(pieces) if len(pieces) > 1 else data
    new_off = np.zeros_like(offsets)
    np.cumsum(new_lens, out=new_off[1:])
    # Rebuild via the native threaded memcpy fan-out (numpy's per-element
    # fancy-index gather was the splice's hot spot).
    return copy_spans(combined, src_base, new_off), new_off


def _column_to_arrow(
    result: "BatchResult", field_id: str, flat: Optional[Any] = None
):
    import pyarrow as pa

    col = result.column(field_id)
    kind = col["kind"]
    overrides = result._overrides.get(field_id, {})
    B = result.lines_read

    if kind == "numeric" and not any(
        isinstance(v, (str, dict)) for v in overrides.values()
    ):
        values = np.asarray(col["values"], dtype=np.int64).copy()
        mask = ~(np.asarray(result.valid) & np.asarray(col["ok"]))
        null = np.asarray(col["null"])
        # Per-line CLF-zero semantics: the format that won the line decides
        # whether '-' means 0 (ConvertCLFIntoNumber) or null.
        null_zero = np.asarray(col["null_zero"])
        values[null & null_zero] = 0
        mask = mask | (null & ~null_zero)
        for row, v in overrides.items():
            if v is None:
                mask[row] = True
            else:
                values[row] = v
                mask[row] = False
        return pa.array(values[:B], type=pa.int64(), mask=mask[:B])

    # Device span columns with no host overrides: build the StringArray
    # straight from (offsets, gathered bytes) with numpy — no per-row
    # Python; URI-repair (`fix`) rows are spliced in individually.  Falls
    # through to the slow path for override rows (host fallback),
    # wildcard maps, and non-UTF-8 data.
    if kind == "span" and not field_id.endswith(".*") and not overrides:
        arr = _spans_to_string_array(result, field_id, flat)
        if arr is not None:
            return arr

    if field_id.endswith(".*"):
        # Wildcard map columns: the flat CSR buffers build the MapArray
        # directly when possible (no per-row dict materialization at all);
        # the dict path handles the exact-semantics leftovers.
        from .batch import _LazyWildcard

        if isinstance(overrides, _LazyWildcard):
            arr = overrides.to_arrow_map(B)
            if arr is not None:
                return arr
        return pa.array(
            [
                None if v is None else list(v.items())
                for v in result.to_pylist(field_id)
            ],
            type=pa.map_(pa.string(), pa.string()),
        )

    # Host-delivered obj columns (GeoIP range-join results, muid decodes):
    # the values already sit in an object ndarray of Python str/int/float —
    # mask the dead rows vectorized and let pyarrow's C-level inference
    # build the array; only mixed-type columns fall back to the per-row
    # stringify path below.
    if kind == "obj":
        vals = np.asarray(col["values"], dtype=object)[:B]
        dead = ~(
            np.asarray(result.valid[:B], dtype=bool)
            & np.asarray(col["ok"][:B], dtype=bool)
        )
        if dead.any() or overrides:
            vals = vals.copy()
            vals[dead] = None
            for row, v in overrides.items():
                vals[row] = v
        try:
            arr = pa.array(vals, from_pandas=True)
            # Keep the batch-to-batch schema stable: an all-null batch
            # must stay a string column (as the per-row path types it),
            # not pa.null() — pa.concat_tables across batches depends on
            # it.  Booleans likewise stringify on the per-row path.
            if not (
                pa.types.is_null(arr.type) or pa.types.is_boolean(arr.type)
            ):
                return arr
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            pass  # mixed types: per-row inference below

    # Host-delivered / span columns: type from the materialized values
    # (host-path numerics — e.g. dissector-produced numbers like GeoIP
    # asn.number — must come out int64/float64, not stringified).
    values_py = result.to_pylist(field_id)
    non_null = [v for v in values_py if v is not None]
    if non_null and all(isinstance(v, int) and not isinstance(v, bool) for v in non_null):
        return pa.array(values_py, type=pa.int64())
    if non_null and all(
        isinstance(v, (int, float)) and not isinstance(v, bool) for v in non_null
    ):
        return pa.array(
            [None if v is None else float(v) for v in values_py],
            type=pa.float64(),
        )
    return pa.array(
        [None if v is None else str(v) for v in values_py], type=pa.string()
    )


def batch_to_arrow(result: "BatchResult", include_validity: bool = True):
    """BatchResult -> pyarrow.Table (one column per requested field)."""
    import pyarrow as pa

    # One threaded multi-column gather covers every flat-eligible span
    # column; ineligible columns (overrides/fix/wildcards) fall through
    # to their per-column paths inside _column_to_arrow.
    flats = result.span_bytes_many(
        [f for f in result.field_ids() if not f.endswith(".*")],
        include_fix=True,
    )
    arrays = []
    names = []
    for field_id in result.field_ids():
        arrays.append(_column_to_arrow(result, field_id, flats.get(field_id)))
        names.append(field_id)
    if include_validity:
        arrays.append(pa.array(np.asarray(result.valid, dtype=bool)))
        names.append("__valid__")
    return pa.table(dict(zip(names, arrays)))


def table_to_ipc_bytes(table) -> bytes:
    """Arrow IPC stream serialization (the cross-process/sidecar format)."""
    import pyarrow as pa

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue()


def table_from_ipc_bytes(data: bytes):
    import pyarrow as pa

    with pa.ipc.open_stream(io.BytesIO(data)) as reader:
        return reader.read_all()


def parse_to_ipc(parser, lines: Sequence[Any]) -> bytes:
    """One-call sidecar surface: lines in, Arrow IPC stream bytes out."""
    return table_to_ipc_bytes(batch_to_arrow(parser.parse_batch(lines)))
