"""Arrow materialization of batch parse results + IPC interop.

SURVEY §7 step 5: "host materializes Arrow arrays ... Java/any-host interop
over Arrow IPC; sidecar service mode".  The reference has no columnar output
(records go through per-line reflection setters); Arrow is the TPU-native
equivalent of that record-delivery surface: span columns gather straight from
the [B, L] byte buffer into a StringArray, numeric columns become int64 with
a null bitmap, wildcard columns become map<string,string>.

Zero-copy note: device span columns build the StringArray from numpy-gathered
(offsets, bytes) buffers wrapped zero-copy — no per-row Python.  Only the
fallback path (host-override rows, wildcard maps, non-UTF-8 data) goes
through ``to_pylist``'s per-row decode.  Numeric columns are pure numpy.
"""
from __future__ import annotations

import io
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Sequence

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from .batch import BatchResult



def _spans_to_string_array(result: "BatchResult", field_id: str) -> Optional[Any]:
    """Vectorized span -> pa.StringArray built on BatchResult.span_bytes
    (the single flat-gather implementation: validity mask, native gather,
    ?&-normalization).  Returns None when the column needs the per-row path
    or the gathered bytes are not valid UTF-8."""
    import pyarrow as pa

    B = result.lines_read
    if B == 0:
        return pa.array([], type=pa.string())
    flat = result.span_bytes(field_id)
    if flat is None:
        return None
    data, offsets64, valid = flat
    if int(offsets64[-1]) > np.iinfo(np.int32).max:
        # int32 StringArray offsets would wrap; don't rely on validate()
        # catching it after the full gather — take the fallback path now.
        return None
    data = np.ascontiguousarray(data)
    offsets = offsets64.astype(np.int32)
    null_bitmap = np.packbits(valid, bitorder="little")
    # pa.py_buffer wraps the numpy arrays zero-copy (buffer protocol);
    # .tobytes() here would duplicate the data buffer per batch.
    arr = pa.StringArray.from_buffers(
        B,
        pa.py_buffer(offsets),
        pa.py_buffer(data),
        pa.py_buffer(null_bitmap),
    )
    try:
        arr.validate(full=True)  # UTF-8 check happens here
    except pa.ArrowInvalid:
        return None
    return arr


def _column_to_arrow(result: "BatchResult", field_id: str):
    import pyarrow as pa

    col = result.column(field_id)
    kind = col["kind"]
    overrides = result._overrides.get(field_id, {})
    B = result.lines_read

    if kind == "numeric" and not any(
        isinstance(v, (str, dict)) for v in overrides.values()
    ):
        values = np.asarray(col["values"], dtype=np.int64).copy()
        mask = ~(np.asarray(result.valid) & np.asarray(col["ok"]))
        null = np.asarray(col["null"])
        # Per-line CLF-zero semantics: the format that won the line decides
        # whether '-' means 0 (ConvertCLFIntoNumber) or null.
        null_zero = np.asarray(col["null_zero"])
        values[null & null_zero] = 0
        mask = mask | (null & ~null_zero)
        for row, v in overrides.items():
            if v is None:
                mask[row] = True
            else:
                values[row] = v
                mask[row] = False
        return pa.array(values[:B], type=pa.int64(), mask=mask[:B])

    # Device span columns with no host overrides: build the StringArray
    # straight from (offsets, gathered bytes) with numpy — no per-row
    # Python.  Falls through to the slow path for override rows (host
    # fallback), rows needing URI micro-materialization (`fix`), wildcard
    # maps, and non-UTF-8 data.
    fix = col.get("fix")
    if (
        kind == "span"
        and not field_id.endswith(".*")
        and not overrides
        and (fix is None or not fix[: result.lines_read].any())
    ):
        arr = _spans_to_string_array(result, field_id)
        if arr is not None:
            return arr

    if field_id.endswith(".*"):
        # Wildcard map columns: the flat CSR buffers build the MapArray
        # directly when possible (no per-row dict materialization at all);
        # the dict path handles the exact-semantics leftovers.
        from .batch import _LazyWildcard

        if isinstance(overrides, _LazyWildcard):
            arr = overrides.to_arrow_map(B)
            if arr is not None:
                return arr
        return pa.array(
            [
                None if v is None else list(v.items())
                for v in result.to_pylist(field_id)
            ],
            type=pa.map_(pa.string(), pa.string()),
        )

    # Host-delivered / span columns: type from the materialized values
    # (host-path numerics — e.g. dissector-produced numbers like GeoIP
    # asn.number — must come out int64/float64, not stringified).
    values_py = result.to_pylist(field_id)
    non_null = [v for v in values_py if v is not None]
    if non_null and all(isinstance(v, int) and not isinstance(v, bool) for v in non_null):
        return pa.array(values_py, type=pa.int64())
    if non_null and all(
        isinstance(v, (int, float)) and not isinstance(v, bool) for v in non_null
    ):
        return pa.array(
            [None if v is None else float(v) for v in values_py],
            type=pa.float64(),
        )
    return pa.array(
        [None if v is None else str(v) for v in values_py], type=pa.string()
    )


def batch_to_arrow(result: "BatchResult", include_validity: bool = True):
    """BatchResult -> pyarrow.Table (one column per requested field)."""
    import pyarrow as pa

    arrays = []
    names = []
    for field_id in result.field_ids():
        arrays.append(_column_to_arrow(result, field_id))
        names.append(field_id)
    if include_validity:
        arrays.append(pa.array(np.asarray(result.valid, dtype=bool)))
        names.append("__valid__")
    return pa.table(dict(zip(names, arrays)))


def table_to_ipc_bytes(table) -> bytes:
    """Arrow IPC stream serialization (the cross-process/sidecar format)."""
    import pyarrow as pa

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue()


def table_from_ipc_bytes(data: bytes):
    import pyarrow as pa

    with pa.ipc.open_stream(io.BytesIO(data)) as reader:
        return reader.read_all()


def parse_to_ipc(parser, lines: Sequence[Any]) -> bytes:
    """One-call sidecar surface: lines in, Arrow IPC stream bytes out."""
    return table_to_ipc_bytes(batch_to_arrow(parser.parse_batch(lines)))
