"""TPU batch execution path: device split programs, vectorized post-stages,
and the columnar batch API."""
from .batch import BatchResult, TpuBatchParser
from .program import DeviceProgram, UnsupportedFormatError, compile_device_program
from .runtime import encode_batch, run_program

__all__ = [
    "BatchResult",
    "TpuBatchParser",
    "DeviceProgram",
    "UnsupportedFormatError",
    "compile_device_program",
    "encode_batch",
    "run_program",
]
