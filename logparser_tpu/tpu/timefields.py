"""Host-side vectorized derivation of timestamp output fields.

The device ships ONE parsed-component bundle per timestamp token (year,
month, day, hour, minute, second, milli, offset_seconds — see
``tpu/timeparse.py``); this module turns that bundle into any of the
TimeStampDissector output fields (TimeStampDissector.java:136-177's 30-output
surface) as whole-column numpy operations — no per-line Python.

All math is int64 numpy.  Epoch math and the civil-date conversions use the
days-from-civil algorithm (proleptic Gregorian); ISO week fields follow the
ISO-8601 Thursday rule, matching ``datetime.date.isocalendar`` which the
host oracle uses.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ..dissectors.timelayout import MONTHS_FULL

Components = Dict[str, np.ndarray]   # int64 arrays, keys as in timeparse


def days_from_civil(y: np.ndarray, m: np.ndarray, d: np.ndarray) -> np.ndarray:
    y = y.astype(np.int64) - (m <= 2)
    era = np.floor_divide(np.where(y >= 0, y, y - 399), 400)
    yoe = y - era * 400
    mp = np.mod(m + 9, 12)
    doy = np.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + np.floor_divide(yoe, 4) - np.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


def civil_from_days(days: np.ndarray):
    """Inverse of days_from_civil: days-since-epoch -> (year, month, day)."""
    z = days.astype(np.int64) + 719468
    era = np.floor_divide(np.where(z >= 0, z, z - 146096), 146097)
    doe = z - era * 146097
    yoe = np.floor_divide(
        doe - np.floor_divide(doe, 1460) + np.floor_divide(doe, 36524)
        - np.floor_divide(doe, 146096),
        365,
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + np.floor_divide(yoe, 4) - np.floor_divide(yoe, 100))
    mp = np.floor_divide(5 * doy + 2, 153)
    d = doy - np.floor_divide(153 * mp + 2, 5) + 1
    m = mp + np.where(mp < 10, 3, -9)
    return y + (m <= 2), m, d


def epoch_millis(c: Components) -> np.ndarray:
    days = days_from_civil(c["year"], c["month"], c["day"])
    sec = c["hour"] * 3600 + c["minute"] * 60 + c["second"] - c["offset_seconds"]
    return (days * 86400 + sec) * 1000 + c["milli"]


def utc_components(c: Components) -> Components:
    """The same instant re-expressed in UTC (ParsedTimestamp.utc_fields)."""
    ms = epoch_millis(c)
    days = np.floor_divide(ms, 86400000)
    ms_day = ms - days * 86400000
    y, m, d = civil_from_days(days)
    return {
        "year": y, "month": m, "day": d,
        "hour": np.floor_divide(ms_day, 3600000),
        "minute": np.mod(np.floor_divide(ms_day, 60000), 60),
        "second": np.mod(np.floor_divide(ms_day, 1000), 60),
        "milli": np.mod(ms_day, 1000),
        "offset_seconds": np.zeros_like(ms),
    }


def iso_week_fields(c: Components):
    """(weekyear, weekofweekyear) per ISO-8601 (the Thursday rule)."""
    days = days_from_civil(c["year"], c["month"], c["day"])
    isodow = np.mod(days + 3, 7) + 1          # 1970-01-01 was a Thursday (4)
    thursday = days - isodow + 4
    ty, _, _ = civil_from_days(thursday)
    jan1 = days_from_civil(ty, np.full_like(ty, 1), np.full_like(ty, 1))
    week = np.floor_divide(thursday - jan1, 7) + 1
    return ty, week


def locale_week_fields(c: Components, first_day: int, min_days: int):
    """(weekyear, weekofweekyear) per java.time ``WeekFields.of(locale)``
    (the vectorized twin of ``timelayout.week_based_fields``); the LOCAL
    week outputs follow the dissector's locale
    (TimeStampDissector.java:455-459) while the ``_utc`` twins stay ISO."""
    y = c["year"].astype(np.int64)
    days = days_from_civil(y, c["month"], c["day"])
    isodow = np.mod(days + 3, 7) + 1
    dow = np.mod(isodow - first_day, 7) + 1
    ones = np.ones_like(y)
    jan1 = days_from_civil(y, ones, ones)
    doy = days - jan1 + 1

    def sow_offset(d):
        week_start = np.mod(d - dow, 7)
        return np.where(week_start + 1 > min_days, 7 - week_start, -week_start)

    offset = sow_offset(doy)
    week = np.floor_divide(7 + offset + doy - 1, 7)
    # week == 0: end-of-week of the previous week-based year.
    prev_len = jan1 - days_from_civil(y - 1, ones, ones)
    doy2 = doy + prev_len
    week_prev = np.floor_divide(7 + sow_offset(doy2) + doy2 - 1, 7)
    # week > 50: possibly the partial week belonging to the next year.
    year_len = days_from_civil(y + 1, ones, ones) - jan1
    new_year_week = np.floor_divide(7 + offset + year_len + min_days - 1, 7)
    spill = (week > 50) & (week >= new_year_week)
    wy = np.where(week == 0, y - 1, np.where(spill, y + 1, y))
    wk = np.where(
        week == 0, week_prev, np.where(spill, week - new_year_week + 1, week)
    )
    return wy, wk


def _zfill(a: np.ndarray, width: int) -> np.ndarray:
    return np.char.zfill(a.astype(np.int64).astype(f"U{width}"), width)


def derive(
    comp: Components, name: str, memo: dict = None, locale=None
) -> np.ndarray:
    """One TimeStampDissector output column from the component bundle.

    ``name`` is the dissector-relative output name (``epoch``, ``year``,
    ``monthname_utc``, ``date``, ...).  Numeric outputs come back int64;
    string outputs come back as numpy unicode arrays.  Pass one ``memo``
    dict per bundle to share the O(B) intermediates (epoch, UTC bundle,
    week pair) across the outputs of the same timestamp.  ``locale``
    (a ``timelayout.LocaleData``) localizes monthname and the LOCAL week
    fields; ``_utc`` week twins stay ISO like the reference
    (TimeStampDissector.java:519-523) while monthname_utc follows the
    locale (:510-511).
    """
    if memo is None:
        memo = {}

    def shared(key, fn):
        if key not in memo:
            memo[key] = fn(comp)
        return memo[key]

    if name == "epoch":
        return shared("epoch", epoch_millis)
    if name.endswith("_utc"):
        utc = shared("utc", utc_components)
        base = name[: -len("_utc")]
        if base in ("weekyear", "weekofweekyear"):
            locale = None  # UTC week twins are always WeekFields.ISO
        return derive(utc, base, memo.setdefault("utc_memo", {}), locale)
    if name in ("year", "month", "day", "hour", "minute", "second"):
        return comp[name]
    if name == "millisecond":
        return comp["milli"]
    if name == "microsecond":
        return comp["milli"] * 1000
    if name == "nanosecond":
        return comp["milli"] * 1000000
    if name in ("weekyear", "weekofweekyear"):
        if locale is not None and (
            locale.week_first_day != 1 or locale.week_min_days != 4
        ):
            pair = shared(
                f"week:{locale.week_first_day}:{locale.week_min_days}",
                lambda c: locale_week_fields(
                    c, locale.week_first_day, locale.week_min_days
                ),
            )
        else:
            pair = shared("isoweek", iso_week_fields)
        return pair[0] if name == "weekyear" else pair[1]
    if name == "monthname":
        table = np.array(
            MONTHS_FULL if locale is None else list(locale.months_full)
        )
        return table[np.clip(comp["month"], 1, 12) - 1]
    if name == "date":
        return np.char.add(
            np.char.add(_zfill(comp["year"], 4), "-"),
            np.char.add(
                np.char.add(_zfill(comp["month"], 2), "-"),
                _zfill(comp["day"], 2),
            ),
        )
    if name == "time":
        return np.char.add(
            np.char.add(_zfill(comp["hour"], 2), ":"),
            np.char.add(
                np.char.add(_zfill(comp["minute"], 2), ":"),
                _zfill(comp["second"], 2),
            ),
        )
    if name == "timezone":
        # The TIME.ZONE/TIME.TIMEZONE quirk, modeled on device: the
        # reference declares ``TIME.ZONE:timezone`` but dissect emits the
        # value under type TIME.TIMEZONE (TestTimeStampDissector.java:258),
        # so a requested timezone field is None on EVERY valid line.  The
        # zone-name string table (timelayout.zone_display_name) feeds only
        # the never-requestable TIME.TIMEZONE emission.  Validity still
        # rides the shared ts bundle: an unparseable timestamp fails the
        # whole line, exactly like every other timestamp output.
        return np.full(comp["year"].shape, None, dtype=object)
    raise KeyError(name)


# Output names the device+host pipeline can deliver, with whether the
# delivered value is numeric (int64 column) or a string column.  The
# TIME.ZONE ``timezone`` output is the declared-but-never-delivered quirk
# (see derive): the device models it as an always-None obj column gated on
# the bundle's parse validity.
_NUMERIC = {
    "epoch", "year", "month", "day", "hour", "minute", "second",
    "millisecond", "microsecond", "nanosecond", "weekyear", "weekofweekyear",
}
_STRING = {"monthname", "date", "time"}

DEVICE_COMPONENTS = (
    _NUMERIC | _STRING
    | {f"{n}_utc" for n in _NUMERIC if n != "epoch"}
    | {f"{n}_utc" for n in _STRING}
    | {"timezone"}
)


def is_numeric_output(name: str) -> bool:
    base = name[: -len("_utc")] if name.endswith("_utc") else name
    return base in _NUMERIC
