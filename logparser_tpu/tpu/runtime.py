"""Batch executor: run a DeviceProgram over ``[B, L]`` uint8 buffers.

All ops are branch-free jnp primitives (masked reductions over the line axis),
so the whole program jit-compiles to one fused XLA computation per
(format, L) pair: no data-dependent Python control flow, static shapes,
everything batched — the XLA-friendly shape of the problem.

Line length handling: lines are padded into power-of-two length buckets
(``encode_batch``) so recompilation is bounded and the MXU/VPU tiles stay
dense.  Overlong lines overflow to the host oracle path.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from .program import DeviceProgram

DEFAULT_MAX_LINE_LEN = 4096


def bucket_length(max_len: int, min_bucket: int = 64,
                  cap: int = DEFAULT_MAX_LINE_LEN) -> int:
    """Smallest power-of-two bucket >= max_len (>= min_bucket, <= cap)."""
    size = min_bucket
    while size < max_len and size < cap:
        size *= 2
    return size


def encode_batch(
    lines: Sequence[Union[bytes, str]],
    line_len: int = 0,
    min_bucket: int = 64,
) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """Pack lines into a padded [B, L] uint8 buffer + lengths.

    Returns (buffer, lengths, overflow_indices); overflowing lines are
    truncated in the buffer and reported for host-side handling.
    """
    raw = [
        line.encode("utf-8") if isinstance(line, str) else line for line in lines
    ]
    max_len = max((len(r) for r in raw), default=1)
    if line_len <= 0:
        line_len = bucket_length(max_len, min_bucket)
    buf = np.zeros((len(raw), line_len), dtype=np.uint8)
    lengths = np.zeros(len(raw), dtype=np.int32)
    overflow: List[int] = []
    for i, r in enumerate(raw):
        if len(r) > line_len:
            overflow.append(i)
            r = r[:line_len]
        buf[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
        lengths[i] = len(r)
    return buf, lengths, overflow


def _find_literal(buf: jnp.ndarray, lengths: jnp.ndarray, lit: bytes,
                  cursor: jnp.ndarray) -> jnp.ndarray:
    """First position >= cursor where `lit` occurs fully inside the line;
    L (=out of range) when absent.  buf: [B, L]; cursor: [B]."""
    B, L = buf.shape
    match = jnp.ones((B, L), dtype=bool)
    for k, byte in enumerate(lit):
        shifted = buf if k == 0 else jnp.roll(buf, -k, axis=1)
        match = match & (shifted == np.uint8(byte))
    pos = jnp.arange(L, dtype=jnp.int32)
    inside = pos[None, :] + len(lit) <= lengths[:, None]
    usable = match & inside & (pos[None, :] >= cursor[:, None])
    cand = jnp.where(usable, pos[None, :], L)
    return jnp.min(cand, axis=1).astype(jnp.int32)


def _run_program_impl(
    program: DeviceProgram,
    buf: jnp.ndarray,
    lengths: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    B, L = buf.shape
    cursor = jnp.zeros(B, dtype=jnp.int32)
    valid = jnp.ones(B, dtype=bool)
    n_tok = len(program.tokens)
    starts = jnp.zeros((n_tok, B), dtype=jnp.int32)
    ends = jnp.zeros((n_tok, B), dtype=jnp.int32)

    pos = jnp.arange(L, dtype=jnp.int32)
    charset_table = jnp.asarray(program.charset_table)

    def check_charset(start, end, spec_charset, spec_min_len, valid):
        cs = charset_table[program.charset_ids[spec_charset]]
        in_span = (pos[None, :] >= start[:, None]) & (pos[None, :] < end[:, None])
        ok_bytes = cs[buf]
        span_ok = jnp.all(ok_bytes | ~in_span, axis=1)
        width = end - start
        # CLF alternations ('number|-'): a lone '-' is legal even though the
        # charset also admits digits; min_len floor of 1 covers both arms.
        return valid & span_ok & (width >= spec_min_len)

    for op in program.ops:
        if op.kind == "lit":
            ok = jnp.ones(B, dtype=bool)
            for k, byte in enumerate(op.lit):
                idx = jnp.clip(cursor + k, 0, L - 1)
                ok = ok & (jnp.take_along_axis(buf, idx[:, None], axis=1)[:, 0]
                           == np.uint8(byte))
            ok = ok & (cursor + len(op.lit) <= lengths)
            valid = valid & ok
            cursor = cursor + len(op.lit)
        elif op.kind == "until_lit":
            found = _find_literal(buf, lengths, op.lit, cursor)
            token_valid = found < L
            start = cursor
            end = jnp.where(token_valid, found, cursor)
            valid = check_charset(start, end, op.charset, op.min_len,
                                  valid & token_valid)
            starts = starts.at[op.token_index].set(start)
            ends = ends.at[op.token_index].set(end)
            cursor = end + len(op.lit)
        elif op.kind == "to_end":
            start = cursor
            end = lengths
            valid = check_charset(start, end, op.charset, op.min_len, valid)
            starts = starts.at[op.token_index].set(start)
            ends = ends.at[op.token_index].set(end)
            cursor = end
        else:  # pragma: no cover
            raise AssertionError(op.kind)

    # The whole line must be consumed (the regex is end-anchored).
    valid = valid & (cursor == lengths)
    return {"starts": starts, "ends": ends, "valid": valid}


def _jitted_for(program: DeviceProgram):
    # One jitted executor per program object (DeviceProgram holds numpy
    # tables, so it is cached by identity on the program itself).
    jitted = getattr(program, "_jitted", None)
    if jitted is None:
        jitted = jax.jit(functools.partial(_run_program_impl, program))
        program._jitted = jitted
    return jitted


def run_program(
    program: DeviceProgram,
    buf: Union[np.ndarray, jnp.ndarray],
    lengths: Union[np.ndarray, jnp.ndarray],
) -> Dict[str, jnp.ndarray]:
    """Execute the split program; returns per-token starts/ends [T, B] and a
    per-line validity mask [B]."""
    return _jitted_for(program)(jnp.asarray(buf), jnp.asarray(lengths))
