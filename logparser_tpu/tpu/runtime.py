"""Batch executor: run a DeviceProgram over ``[B, L]`` uint8 buffers.

All ops are branch-free jnp primitives (masked reductions over the line axis),
so the whole program jit-compiles to one fused XLA computation per
(format, L) pair: no data-dependent Python control flow, static shapes,
everything batched — the XLA-friendly shape of the problem.

Line length handling: lines are padded into a small set of length buckets
(``encode_batch``; 128-multiples in the common range, coarser above — see
native._bucket) so recompilation is bounded and the VPU tiles stay dense.  Overlong lines overflow to the host oracle path.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from .program import DeviceProgram

# The packed span slots are 13 bits (pipeline._SPAN_BITS), so the device
# path handles lines up to 8191 bytes; only longer lines overflow to the
# host oracle.
DEFAULT_MAX_LINE_LEN = 8191


def bucket_length(max_len: int, min_bucket: int = 64,
                  cap: int = DEFAULT_MAX_LINE_LEN) -> int:
    """Smallest bucket >= max_len (>= min_bucket, <= cap).  Finer buckets
    than powers of two in the common range (316-byte lines pad to 384, not
    512 — the [B, L] passes scale with padding) without exploding the number
    of compiled shapes; see native._bucket, the single implementation."""
    from ..native import _bucket

    return _bucket(max_len, min_bucket, cap)


def encode_batch(
    lines: Sequence[Union[bytes, str]],
    line_len: int = 0,
    min_bucket: int = 64,
) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """Pack lines into a padded [B, L] uint8 buffer + lengths.

    Returns (buffer, lengths, overflow_indices); overflowing lines are
    truncated in the buffer and reported for host-side handling.
    """
    # One trailing '\n' is invisible to the host regex (Python '$' matches
    # before a final newline, so the oracle parses such lines identically)
    # — strip it so the device automaton and its plausibility anchoring
    # see exactly what the regex effectively parses.  Only ONE newline:
    # '$' skips only the last.
    raw = []
    for line in lines:
        b = line.encode("utf-8") if isinstance(line, str) else line
        if b.endswith(b"\n"):
            b = b[:-1]
        raw.append(b)
    # Native fast path: join + C++ frame/pack (logparser_tpu/native).  Only
    # safe when re-framing the joined blob reproduces the list exactly — no
    # embedded newlines, no trailing '\r' the framer would strip.
    if raw:
        from ..native import encode_blob, native_available

        if native_available() and not any(
            b"\n" in r or r.endswith(b"\r") or not r for r in raw
        ):
            buf, lengths, overflow = encode_blob(
                b"\n".join(raw), line_len, min_bucket,
                cap=DEFAULT_MAX_LINE_LEN,
            )
            if buf.shape[0] == len(raw):
                return buf, lengths, overflow
    max_len = max((len(r) for r in raw), default=1)
    if line_len <= 0:
        line_len = bucket_length(max_len, min_bucket)
    buf = np.zeros((len(raw), line_len), dtype=np.uint8)
    lengths = np.zeros(len(raw), dtype=np.int32)
    overflow: List[int] = []
    for i, r in enumerate(raw):
        if len(r) > line_len:
            overflow.append(i)
            r = r[:line_len]
        buf[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
        lengths[i] = len(r)
    return buf, lengths, overflow


def _run_program_impl(
    program: DeviceProgram,
    buf: jnp.ndarray,
    lengths: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Back-compat wrapper over the shared split pipeline (pipeline.py)."""
    from .pipeline import compute_split

    starts, ends, valid, _, _ = compute_split(program, buf.astype(jnp.int32), lengths)
    return {
        "starts": jnp.stack(starts),
        "ends": jnp.stack(ends),
        "valid": valid,
    }


def _jitted_for(program: DeviceProgram):
    # One jitted executor per program object (DeviceProgram holds numpy
    # tables, so it is cached by identity on the program itself).
    jitted = getattr(program, "_jitted", None)
    if jitted is None:
        jitted = jax.jit(functools.partial(_run_program_impl, program))
        program._jitted = jitted
    return jitted


def run_program(
    program: DeviceProgram,
    buf: Union[np.ndarray, jnp.ndarray],
    lengths: Union[np.ndarray, jnp.ndarray],
) -> Dict[str, jnp.ndarray]:
    """Execute the split program; returns per-token starts/ends [T, B] and a
    per-line validity mask [B]."""
    return _jitted_for(program)(jnp.asarray(buf), jnp.asarray(lengths))
