"""AOT parser executables + a persistent cross-process compile cache.

Every tier of the system pays XLA compile latency at the worst possible
moment: a sidecar's first request on a fresh shape bucket, a front-tier
respawn, a pod host's first batch.  This module makes the compiled parser
executable a durable, shareable artifact instead of a per-process side
effect:

- :class:`AotExecutor` wraps the ``jax.jit`` executor built by
  ``pipeline.build_units_jnp_fn`` with an EXPLICIT per-shape
  lower -> compile path (``jit.lower(ShapeDtypeStruct...).compile()``),
  so compile cost is attributable (``parser_compile_seconds_total{phase}``)
  and the compiled object is serializable
  (``jax.experimental.serialize_executable``).
- :class:`CompileCache` is the content-addressed on-disk store
  (``LOGPARSER_TPU_COMPILE_CACHE`` dir).  Keys hash the parser program
  fingerprint, the (B, L) shape bucket, and the backend/jax version —
  a mismatch on ANY component is a miss and a fresh compile, never a
  wrong kernel.  The host oracle stays the exactness referee regardless:
  a cache bug can cost a compile, not a byte of output.
- Artifacts (``TpuBatchParser.to_bytes`` v2) embed serialized executables
  so a fresh host loading an artifact executes its first batch without
  lowering anything (phase=deserialize only).

The pytree structure of the executor's calling convention is FIXED
((buf [B, L] uint8, lengths [B] int32) -> packed int32 array), so cache
entries carry only the serialized payload; the in/out treedefs are
reconstructed from ShapeDtypeStructs at load time (pickling PyTreeDefs is
not portable across processes).
"""
from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import struct
import threading
import time
from dataclasses import is_dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

ENV_CACHE_DIR = "LOGPARSER_TPU_COMPILE_CACHE"

# Entry format version: bump when the on-disk layout changes.  Old entries
# then simply miss (refused by magic), they are never misread.
_ENTRY_MAGIC = b"LPTPU-EXEC-v1\n"

# Default shape-bucket ladder for prewarm/artifact embedding: the batch
# buckets serving traffic actually hits (service chunks, feeder chunks,
# coalesced batches all pad to powers of two >= 64).
DEFAULT_BUCKET_LADDER = (64, 256, 1024)


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

_code_fp: Optional[str] = None
_code_fp_lock = threading.Lock()


def code_fingerprint() -> str:
    """Content hash of the device-pipeline sources.  Any edit to the code
    that shapes the compiled computation invalidates every cache key —
    coarse, but it can never reuse a stale kernel."""
    global _code_fp
    if _code_fp is None:
        with _code_fp_lock:
            if _code_fp is None:
                h = hashlib.blake2b(digest_size=12)
                root = os.path.dirname(os.path.abspath(__file__))
                for name in sorted(os.listdir(root)):
                    if not name.endswith(".py"):
                        continue
                    with open(os.path.join(root, name), "rb") as f:
                        h.update(name.encode())
                        h.update(f.read())
                _code_fp = h.hexdigest()
    return _code_fp


def backend_fingerprint() -> str:
    """jax/jaxlib version + backend platform + device kind: a serialized
    executable is only loadable into the exact runtime that produced it."""
    import jax

    try:
        devs = jax.devices()
        kind = devs[0].device_kind if devs else "none"
        platform = devs[0].platform if devs else jax.default_backend()
    except Exception:  # uninitialized backend: still a stable string
        kind, platform = "none", "unknown"
    jaxlib_version = getattr(
        getattr(jax, "_src", None), "lib", None
    )
    jl = getattr(jaxlib_version, "version_str", None) or jax.__version__
    return f"jax={jax.__version__};jaxlib={jl};backend={platform};kind={kind}"


def _slot_names(x: Any) -> tuple:
    """All ``__slots__`` names across the MRO (``__slots__`` may be a
    bare string), minus the pseudo-slots."""
    names = []
    for klass in type(x).__mro__:
        slots = klass.__dict__.get("__slots__", ())
        if isinstance(slots, str):
            slots = (slots,)
        names.extend(s for s in slots if s not in ("__dict__", "__weakref__"))
    return tuple(names)


def stable_hash(obj: Any, digest_size: int = 16) -> str:
    """Deterministic cross-process content hash of a (mostly) pure-data
    object graph: primitives, containers, numpy arrays, dataclasses and
    plain ``__dict__``/``__slots__`` objects.  Sets are sorted by repr;
    anything opaque hashes by type name + repr — possibly
    process-unstable, which can only cost a cache miss, never a wrong
    hit."""
    h = hashlib.blake2b(digest_size=digest_size)

    def feed(x: Any, depth: int = 0) -> None:
        if depth > 24:
            h.update(b"<deep>")
            return
        if x is None or isinstance(x, (bool, int, float, str, bytes)):
            h.update(repr(x).encode())
        elif isinstance(x, np.ndarray):
            h.update(f"nd:{x.dtype}:{x.shape}".encode())
            h.update(np.ascontiguousarray(x).tobytes())
        elif isinstance(x, np.generic):
            h.update(repr(x.item()).encode())
        elif isinstance(x, (list, tuple)):
            h.update(f"seq{len(x)}(".encode())
            for item in x:
                feed(item, depth + 1)
                h.update(b",")
            h.update(b")")
        elif isinstance(x, dict):
            h.update(f"map{len(x)}(".encode())
            for k in sorted(x, key=repr):
                feed(k, depth + 1)
                h.update(b"=")
                feed(x[k], depth + 1)
                h.update(b",")
            h.update(b")")
        elif isinstance(x, (set, frozenset)):
            h.update(f"set{len(x)}(".encode())
            for item in sorted(x, key=repr):
                feed(item, depth + 1)
                h.update(b",")
            h.update(b")")
        elif is_dataclass(x) or hasattr(x, "__dict__") or _slot_names(x):
            # __slots__ classes have no __dict__; without this branch
            # they'd fall through to the default repr, whose memory
            # address makes the fingerprint process-unique and silently
            # defeats the cross-process cache for any parser whose plan
            # graph contains one (e.g. locale tables under TIME fields).
            h.update(type(x).__name__.encode())
            state = dict(getattr(x, "__dict__", {}))
            for slot in _slot_names(x):
                if hasattr(x, slot):
                    state[slot] = getattr(x, slot)
            feed(state, depth + 1)
        else:
            h.update(f"{type(x).__name__}:{x!r}".encode())

    feed(obj)
    return h.hexdigest()


# ---------------------------------------------------------------------------
# the on-disk store
# ---------------------------------------------------------------------------


def _metrics():
    from ..observability import metrics

    return metrics()


def _warn_once(message: str) -> None:
    from ..observability import log_warning_once

    log_warning_once(logger, message)


class CompileCache:
    """Content-addressed executable store: one file per (fingerprint,
    shape, backend) key under the cache root.  Writes are atomic
    (tmp + rename), reads verify magic + header + payload digest —
    a corrupted or version-mismatched entry is refused (miss + warn-once +
    ``compile_cache_errors_total``), never loaded."""

    def __init__(self, root: Optional[str]) -> None:
        self.root = root or None

    @classmethod
    def from_env(cls) -> "CompileCache":
        return cls(os.environ.get(ENV_CACHE_DIR) or None)

    @property
    def enabled(self) -> bool:
        return self.root is not None

    def _path(self, key: str) -> str:
        assert self.root is not None
        return os.path.join(self.root, key[:2], f"{key}.xc")

    # -- read ------------------------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        """The serialized executable payload for ``key``, or None.  Every
        failure mode (missing, corrupt, version drift) is a miss."""
        if not self.enabled:
            return None
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        except OSError as exc:
            _metrics().increment("compile_cache_errors_total",
                                 labels={"kind": "io"})
            _warn_once(f"compile cache read failed ({path}): {exc}")
            return None
        entry = self._decode(blob, key, path)
        return entry

    def _decode(self, blob: bytes, key: str, path: str) -> Optional[bytes]:
        reg = _metrics()
        if not blob.startswith(_ENTRY_MAGIC):
            reg.increment("compile_cache_errors_total",
                          labels={"kind": "magic"})
            _warn_once(f"compile cache entry refused (bad magic): {path}")
            return None
        try:
            off = len(_ENTRY_MAGIC)
            (hlen,) = struct.unpack("<I", blob[off:off + 4])
            header = json.loads(blob[off + 4:off + 4 + hlen])
            payload = blob[off + 4 + hlen:]
        except Exception:
            reg.increment("compile_cache_errors_total",
                          labels={"kind": "corrupt"})
            _warn_once(f"compile cache entry refused (corrupt): {path}")
            return None
        if header.get("key") != key:
            reg.increment("compile_cache_errors_total",
                          labels={"kind": "key_mismatch"})
            _warn_once(f"compile cache entry refused (key mismatch): {path}")
            return None
        if header.get("backend") != backend_fingerprint():
            # Same key hash can't collide across backends (the backend is
            # hashed into the key), so this only trips when a file was
            # copied around — refuse it like any other corruption.
            reg.increment("compile_cache_errors_total",
                          labels={"kind": "backend"})
            _warn_once(f"compile cache entry refused (backend drift): {path}")
            return None
        digest = hashlib.blake2b(payload, digest_size=16).hexdigest()
        if header.get("digest") != digest:
            reg.increment("compile_cache_errors_total",
                          labels={"kind": "digest"})
            _warn_once(f"compile cache entry refused (payload digest): {path}")
            return None
        return payload

    # -- write -----------------------------------------------------------

    def put(self, key: str, payload: bytes,
            meta: Optional[Dict[str, Any]] = None) -> bool:
        """Store a serialized executable.  IO failures are swallowed with a
        warn-once (the cache is an accelerator, not a correctness
        dependency)."""
        if not self.enabled:
            return False
        path = self._path(key)
        header = dict(meta or {})
        header.update({
            "key": key,
            "backend": backend_fingerprint(),
            "digest": hashlib.blake2b(payload, digest_size=16).hexdigest(),
            "created": time.time(),
        })
        hdr = json.dumps(header, sort_keys=True).encode()
        blob = _ENTRY_MAGIC + struct.pack("<I", len(hdr)) + hdr + payload
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)  # atomic: readers see old or new, whole
            _metrics().increment("compile_cache_writes_total")
            return True
        except OSError as exc:
            _metrics().increment("compile_cache_errors_total",
                                 labels={"kind": "io"})
            _warn_once(f"compile cache write failed ({path}): {exc}")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False


# ---------------------------------------------------------------------------
# the AOT executor
# ---------------------------------------------------------------------------


def _phase(reg, phase: str, seconds: float) -> None:
    reg.increment("parser_compile_total", labels={"phase": phase})
    reg.increment("parser_compile_seconds_total", seconds,
                  labels={"phase": phase})


class AotExecutor:
    """Drop-in callable for the ``jax.jit`` parser executor with explicit
    per-shape AOT compilation and a persistent executable cache.

    Resolution order per (B, L) shape bucket: in-memory map (artifact
    preloads land here) -> disk cache (``LOGPARSER_TPU_COMPILE_CACHE``)
    -> explicit lower + compile (then written back to disk).  Each phase is
    timed into ``parser_compile_seconds_total{phase=lower|compile|
    serialize|deserialize}``.

    Compile/execute ERRORS propagate unchanged — the device fault layer
    (device_faults.classify_device_error) owns those semantics; only cache
    IO/corruption degrades, into a fresh compile."""

    def __init__(
        self,
        jit_fn: Callable,
        fingerprint: str,
        serializable: bool = True,
        cache: Optional[CompileCache] = None,
    ) -> None:
        self._jit = jit_fn
        self.fingerprint = fingerprint
        # Mesh-sharded executors compile against THIS process's device
        # set; their serialized form is not portable, so they AOT-compile
        # in memory but skip the disk/artifact round-trip.
        self.serializable = serializable
        self._cache = cache
        self._execs: Dict[Tuple[int, int], Callable] = {}
        self._payloads: Dict[Tuple[int, int], bytes] = {}
        self._lock = threading.Lock()

    # -- plumbing --------------------------------------------------------

    def cache(self) -> CompileCache:
        # Env is re-read per resolution (cheap, and lets tests/tools
        # repoint the dir without process surgery) unless a cache was
        # injected explicitly.
        return self._cache if self._cache is not None else CompileCache.from_env()

    def _key(self, b: int, l: int) -> str:
        raw = f"{self.fingerprint}|{b}x{l}|{backend_fingerprint()}"
        return hashlib.blake2b(raw.encode(), digest_size=20).hexdigest()

    def _avals(self, b: int, l: int):
        import jax
        import jax.numpy as jnp

        return (
            jax.ShapeDtypeStruct((b, l), jnp.uint8),
            jax.ShapeDtypeStruct((b,), jnp.int32),
        )

    # -- resolution ------------------------------------------------------

    def __call__(self, buf, lengths):
        import jax

        if isinstance(buf, jax.core.Tracer) or isinstance(lengths, jax.core.Tracer):
            # Under a JAX transformation (eval_shape, grad-of, nested
            # jit): AOT executables reject tracers, so trace through the
            # plain jitted function instead.
            return self._jit(buf, lengths)
        b, l = int(buf.shape[0]), int(buf.shape[1])
        exe = self._execs.get((b, l))
        if exe is None:
            exe = self._resolve(b, l)
        return exe(buf, lengths)

    def warm(self, b: int, l: int) -> str:
        """Ensure shape (b, l) is executable without compiling on the
        request path.  Returns where it came from: ``"memory"`` | ``"disk"``
        | ``"compiled"``."""
        with self._lock:
            if (b, l) in self._execs:
                return "memory"
        before = _metrics().get("compile_cache_hits_total")
        self._resolve(b, l)
        after = _metrics().get("compile_cache_hits_total")
        return "disk" if after > before else "compiled"

    def shapes(self) -> List[Tuple[int, int]]:
        with self._lock:
            return sorted(self._execs)

    def _resolve(self, b: int, l: int) -> Callable:
        with self._lock:
            exe = self._execs.get((b, l))
            if exe is not None:
                return exe
            reg = _metrics()
            exe = self._try_load(b, l, reg)
            if exe is None:
                exe = self._compile(b, l, reg)
            self._execs[(b, l)] = exe
            return exe

    def _try_load(self, b: int, l: int, reg) -> Optional[Callable]:
        if not self.serializable:
            return None
        cache = self.cache()
        if not cache.enabled:
            return None
        key = self._key(b, l)
        payload = cache.get(key)
        if payload is None:
            reg.increment("compile_cache_misses_total")
            return None
        exe = self._deserialize(payload, b, l, reg)
        if exe is None:
            reg.increment("compile_cache_misses_total")
            return None
        reg.increment("compile_cache_hits_total")
        self._payloads[(b, l)] = payload
        return exe

    def _deserialize(self, payload: bytes, b: int, l: int, reg
                     ) -> Optional[Callable]:
        """Load a serialized executable; any failure is a refusal (fresh
        compile), counted and warned once — never an abort."""
        from jax.experimental import serialize_executable as se
        import jax
        import jax.tree_util as jtu

        t0 = time.perf_counter()
        try:
            avals = self._avals(b, l)
            in_tree = jtu.tree_structure((avals, {}))
            out_tree = jtu.tree_structure(jax.eval_shape(self._jit, *avals))
            exe = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as exc:
            reg.increment("compile_cache_errors_total",
                          labels={"kind": "deserialize"})
            _warn_once(
                f"cached executable refused (deserialize failed, shape "
                f"{b}x{l}): {type(exc).__name__}: {exc}"
            )
            return None
        _phase(reg, "deserialize", time.perf_counter() - t0)
        return exe

    def _compile(self, b: int, l: int, reg) -> Callable:
        """Explicit lower -> compile (errors propagate: the fault layer's
        compile-demotion semantics key on them), then serialize + write
        back when the executor is disk-eligible."""
        avals = self._avals(b, l)
        t0 = time.perf_counter()
        lowered = self._jit.lower(*avals)
        t1 = time.perf_counter()
        _phase(reg, "lower", t1 - t0)
        compiled = lowered.compile()
        t2 = time.perf_counter()
        _phase(reg, "compile", t2 - t1)
        if self.serializable:
            # Serialize only when there is a cache to write back to —
            # serialization costs a noticeable fraction of the compile
            # itself, and artifact export (export_payloads) serializes
            # lazily for shapes skipped here.
            cache = self.cache()
            if cache.enabled:
                payload = self._serialize(compiled, b, l, reg)
                if payload is not None:
                    self._payloads[(b, l)] = payload
                    cache.put(self._key(b, l), payload, meta={
                        "shape": [b, l], "fingerprint": self.fingerprint,
                    })
        return compiled

    def _serialize(self, compiled, b: int, l: int, reg) -> Optional[bytes]:
        from jax.experimental import serialize_executable as se

        t0 = time.perf_counter()
        try:
            payload, _, _ = se.serialize(compiled)
        except Exception as exc:
            reg.increment("compile_cache_errors_total",
                          labels={"kind": "serialize"})
            _warn_once(
                f"executable not serializable (shape {b}x{l}): "
                f"{type(exc).__name__}: {exc}"
            )
            return None
        _phase(reg, "serialize", time.perf_counter() - t0)
        return payload

    # -- artifact integration -------------------------------------------

    def export_payloads(self) -> Dict[Tuple[int, int], bytes]:
        """Serialized executables for every compiled/loaded shape (used by
        ``TpuBatchParser.to_bytes`` to embed them in the artifact)."""
        with self._lock:
            out = dict(self._payloads)
            missing = [s for s in self._execs if s not in out]
        reg = _metrics()
        for (b, l) in missing:
            payload = self._serialize(self._execs[(b, l)], b, l, reg)
            if payload is not None:
                with self._lock:
                    self._payloads[(b, l)] = payload
                out[(b, l)] = payload
        return out

    def preload(self, b: int, l: int, payload: bytes,
                backend: Optional[str] = None) -> bool:
        """Install an artifact-embedded executable for shape (b, l).
        Refused (False) on backend drift or a broken payload — the shape
        then simply compiles fresh on first use."""
        if not self.serializable:
            return False
        if backend is not None and backend != backend_fingerprint():
            _metrics().increment("compile_cache_errors_total",
                                 labels={"kind": "backend"})
            _warn_once(
                "artifact executable refused (backend drift): "
                f"{backend!r} != {backend_fingerprint()!r}"
            )
            return False
        reg = _metrics()
        exe = self._deserialize(payload, b, l, reg)
        if exe is None:
            return False
        with self._lock:
            self._execs[(b, l)] = exe
            self._payloads[(b, l)] = payload
        return True
