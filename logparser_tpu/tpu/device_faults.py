"""Device-tier fault layer (docs/FAULTS.md "Device failure model").

Rounds 11-16 made every PROCESS tier survive crashes (supervised feeder
workers, sidecar fleet failover, exactly-once pod jobs); the accelerator
itself was the last unsupervised single point of failure: a device OOM
on an oversized bucket, a wedged XLA execution, or a failed jit compile
aborted the batch, the session, or the whole pod job.  This module holds
the jax-free pieces of the recovery machinery ``tpu/batch.py`` composes
around the executor:

- the typed fault vocabulary (:class:`DeviceOomError` & friends) and the
  :func:`classify_device_error` rule that maps raw XLA/jax exceptions
  onto it;
- :class:`DeviceFaultPolicy` — the recovery knobs (bisect depth, clamp
  trigger, breaker threshold/cool-off);
- :class:`DeviceBreaker` — the per-parser-key circuit breaker that
  demotes a repeatedly-faulting compiled kernel to the host oracle (the
  device twin of the feeder's ``demote_transport`` ladder): a pure
  decision machine with an explicit ``now`` so tests drive it directly;
- :func:`run_with_deadline` — the abandonable-worker idiom from the
  serving tier's ``request_deadline_s`` (PR 7) one level down: a wedged
  XLA execution expires instead of hanging the pipeline, and the
  abandoned thread finishes (or not) in the background;
- :func:`resolve_budget` / :func:`resolve_deadline` — the
  ``LOGPARSER_TPU_DEVICE_BYTES_BUDGET`` / ``LOGPARSER_TPU_DEVICE_DEADLINE_S``
  env fallbacks behind the ``TpuBatchParser`` kwargs.

Deliberately NO jax import at module level: ``tools/chaos.py`` raises
the typed faults from injection hooks and the service tier classifies
:class:`DeviceBudgetError`, both in processes that must not pay (or may
not have) a device runtime.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

#: Pre-allocation device-memory ceiling (bytes).  The batch-tier twin of
#: the serving tier's frame ceilings: validated BEFORE ``device_put``,
#: answering a structured :class:`DeviceBudgetError` instead of an XLA
#: RESOURCE_EXHAUSTED abort.  Unset/0 = disabled.
BUDGET_ENV = "LOGPARSER_TPU_DEVICE_BYTES_BUDGET"

#: Per-execution deadline (seconds) for the blocking side of a device
#: batch (dispatch + packed fetch).  Unset/0 = disabled (no worker
#: thread on the hot path).
DEADLINE_ENV = "LOGPARSER_TPU_DEVICE_DEADLINE_S"


class DeviceFault(Exception):
    """Base class of every classified device-tier fault."""


class DeviceOomError(DeviceFault):
    """Device RESOURCE_EXHAUSTED (allocation or execution OOM)."""


class DeviceCompileError(DeviceFault):
    """jit trace/lowering/compilation failed — deterministic, so the
    parser key demotes to the host oracle permanently (warn-once)."""


class DeviceWedgeError(DeviceFault):
    """A device execution exceeded its deadline (wedged kernel / hung
    transfer); the batch reroutes to the batched oracle host path."""


class DeviceExecutionError(DeviceFault):
    """Any other device-side runtime failure (halted device, preempted
    slice, transfer error) — transient until the breaker says otherwise."""


class DeviceBudgetError(DeviceFault):
    """Structured pre-allocation reject: the batch's estimated device
    footprint exceeds the configured byte budget.  Raised BEFORE any
    ``device_put`` — the caller (service tier, jobs) answers it as a
    structured reject instead of letting XLA OOM."""

    def __init__(self, estimated_bytes: int, budget_bytes: int,
                 lines: int):
        self.estimated_bytes = int(estimated_bytes)
        self.budget_bytes = int(budget_bytes)
        self.lines = int(lines)
        super().__init__(
            f"device byte budget exceeded: batch of {lines} lines needs "
            f"~{self.estimated_bytes} device bytes, budget is "
            f"{self.budget_bytes} ({BUDGET_ENV} / device_bytes_budget)"
        )


# Message markers, lower-cased.  RESOURCE_EXHAUSTED is XLA's canonical
# OOM status; the rest cover pjrt allocator phrasing across backends.
_OOM_MARKERS = (
    "resource_exhausted", "resource exhausted", "out of memory", "oom",
    "failed to allocate",
)
# Deterministic compile-side failures: retrying the same shape would
# fail identically, so these demote the key instead of rerouting once.
# Deliberately NARROW (no bare "lowering", no INVALID_ARGUMENT): a
# misclassified transient would latch the permanent demotion, while a
# real compile failure misread as "execute" still demotes via the
# breaker after `breaker_threshold` repeats — the safe direction.
_COMPILE_MARKERS = (
    "unimplemented", "compilation failure", "failed to compile",
    "error during lowering", "mosaic",
)


def classify_device_error(e: BaseException) -> str:
    """``"oom"`` | ``"compile"`` | ``"wedge"`` | ``"execute"`` for any
    exception the executor path can raise.  Typed :class:`DeviceFault`
    subclasses (including chaos-injected ones) classify by type; raw
    XLA/jax errors by message marker, defaulting to the transient
    ``"execute"`` class (reroute once, demote only via the breaker)."""
    if isinstance(e, DeviceOomError):
        return "oom"
    if isinstance(e, DeviceCompileError):
        return "compile"
    if isinstance(e, DeviceWedgeError):
        return "wedge"
    if isinstance(e, DeviceExecutionError):
        return "execute"
    msg = f"{type(e).__name__}: {e}".lower()
    if any(m in msg for m in _OOM_MARKERS):
        return "oom"
    if any(m in msg for m in _COMPILE_MARKERS):
        return "compile"
    return "execute"


def resolve_budget(explicit: Optional[int]) -> Optional[int]:
    """The effective device byte budget: the explicit kwarg wins, else
    the env var; 0/absent/garbage = disabled (None)."""
    if explicit is not None:
        return int(explicit) or None
    raw = os.environ.get(BUDGET_ENV, "").strip()
    if not raw:
        return None
    try:
        return int(raw) or None
    except ValueError:
        return None


def resolve_deadline(explicit: Optional[float]) -> Optional[float]:
    """The effective per-execution deadline (seconds); 0/absent =
    disabled — the hot path then runs with no worker thread at all."""
    if explicit is not None:
        return float(explicit) or None
    raw = os.environ.get(DEADLINE_ENV, "").strip()
    if not raw:
        return None
    try:
        return float(raw) or None
    except ValueError:
        return None


@dataclass
class DeviceFaultPolicy:
    """Recovery tunables (all have safe defaults)."""

    #: Max bisect depth per batch on RESOURCE_EXHAUSTED: each level
    #: halves the row range, so 4 levels retry down to B/16 before the
    #: batch reroutes to the oracle.
    oom_retries: int = 4
    #: OOM events before the parser PERMANENTLY clamps its max executed
    #: bucket below the failing size (``device_bucket_clamped`` gauge):
    #: the first OOM is forgiven as transient; repetition is geometry.
    oom_clamp_after: int = 2
    #: Bisect floor — a batch that OOMs at/below this row count cannot
    #: be saved by splitting and reroutes to the oracle.
    min_bucket: int = 64
    #: Consecutive non-compile device faults before the breaker opens
    #: (kernel demoted to the host oracle).
    breaker_threshold: int = 3
    #: Seconds an open breaker waits before re-admitting device batches
    #: (the half-open trial window).
    breaker_cooloff_s: float = 30.0


class DeviceBreaker:
    """Per-parser-key circuit breaker over the compiled kernel — the
    device twin of the feeder's transport-demotion ladder.

    closed -> (``threshold`` consecutive faults) -> open (every batch
    reroutes to the oracle) -> after ``cooloff_s`` device batches are
    re-admitted; the first fault re-opens, the first success closes.
    ``record_fault(permanent=True)`` (compile failure) latches open
    forever — retrying a deterministic compile failure is pure waste.

    Thread-safe (one lock; the serving tier shares a parser across
    sessions) and a pure time machine: every method takes an explicit
    ``now`` so tests drive the clock.
    """

    def __init__(self, threshold: int = 3, cooloff_s: float = 30.0):
        self.threshold = max(1, int(threshold))
        self.cooloff_s = float(cooloff_s)
        self.consecutive = 0
        self.opened_at: Optional[float] = None
        self.permanent = False
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        if self.permanent:
            return "demoted"
        if self.opened_at is None:
            return "closed"
        return "open"

    def allow(self, now: Optional[float] = None) -> bool:
        """May the next batch try the device?  Read-only: open simply
        waits out the cool-off, then batches flow again (half-open by
        time, not by a single claimed trial — several stream batches may
        be in flight, and a still-broken device re-trips immediately)."""
        with self._lock:
            if self.permanent:
                return False
            if self.opened_at is None:
                return True
            now = time.monotonic() if now is None else now
            return (now - self.opened_at) >= self.cooloff_s

    def record_success(self, now: Optional[float] = None) -> None:
        with self._lock:
            if not self.permanent:
                self.consecutive = 0
                self.opened_at = None

    def record_fault(self, now: Optional[float] = None,
                     permanent: bool = False) -> bool:
        """One device fault landed.  Returns True exactly when THIS
        fault transitioned the breaker to open/demoted — the caller's
        cue to warn-once and count the demotion."""
        with self._lock:
            now = time.monotonic() if now is None else now
            if permanent:
                was = self.permanent
                self.permanent = True
                self.opened_at = now
                return not was
            if self.permanent:
                return False
            self.consecutive += 1
            if self.opened_at is not None:
                # Fault during/after the cool-off window: re-open.
                self.opened_at = now
                return False
            if self.consecutive >= self.threshold:
                self.opened_at = now
                return True
            return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_faults": self.consecutive,
            }


def run_with_deadline(work: Callable[[], Any], deadline_s: float,
                      label: str = "execute") -> Any:
    """Run ``work`` on an abandonable daemon worker; raise
    :class:`DeviceWedgeError` when it misses the deadline.  The PR-7
    ``request_deadline_s`` idiom one level down: the worker keeps
    running (and logs nothing) after abandonment — a wedged XLA call
    cannot be cancelled, only walked away from."""
    box: dict = {}
    done = threading.Event()

    def run() -> None:
        try:
            box["value"] = work()
        except BaseException as e:  # noqa: BLE001 — relayed to the waiter
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, name=f"lp-device-{label}",
                         daemon=True)
    t.start()
    if not done.wait(deadline_s):
        raise DeviceWedgeError(
            f"device {label} exceeded its {deadline_s:.3f}s deadline "
            "(wedged execution abandoned; batch reroutes to the host "
            "oracle)"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]
